"""MNIST-style training from a petastorm_tpu dataset with the JAX adapter.

Reference analogue: ``examples/mnist/`` (downloads real MNIST and trains
TF/torch models). Here the digits are synthetic (no egress) and the model is
``petastorm_tpu.models.mnist_mlp`` — the pipeline is identical to what real
MNIST parquet would use.
"""

import tempfile

import numpy as np

from petastorm_tpu import make_reader, materialize_dataset
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.jax_utils import JaxDataLoader
from petastorm_tpu.unischema import Unischema, UnischemaField

MnistSchema = Unischema('MnistSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    UnischemaField('digit', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (28, 28), NdarrayCodec(), False),
])


def generate_synthetic_mnist(output_url, n=2048, seed=0):
    """Class-dependent blob images: learnable, standalone, deterministic."""
    rng = np.random.default_rng(seed)

    def row(i):
        digit = int(rng.integers(0, 10))
        img = rng.integers(0, 30, (28, 28), dtype=np.uint8)
        r, c = divmod(digit, 4)
        img[5 + 6 * r: 11 + 6 * r, 3 + 6 * c: 9 + 6 * c] += 200
        return {'idx': np.int64(i), 'digit': np.int64(digit), 'image': img}

    with materialize_dataset(output_url, MnistSchema, rows_per_file=512) as w:
        w.write_rows(row(i) for i in range(n))


def train(dataset_url, epochs=5, lr=5e-2, batch_size=64):
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.models import mnist_mlp

    params = mnist_mlp.init(jax.random.PRNGKey(0))
    for epoch in range(epochs):
        with make_reader(dataset_url, num_epochs=1, seed=epoch,
                         workers_count=4) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   shuffling_queue_capacity=512, seed=epoch)
            losses, accs = [], []
            for batch in loader:
                images = jnp.asarray(
                    batch['image'].reshape(len(batch['image']), -1),
                    jnp.float32) / 255.0
                labels = jnp.asarray(batch['digit'])
                params, loss = mnist_mlp.train_step(params, images, labels, lr)
                losses.append(float(loss))
                accs.append(float(mnist_mlp.accuracy(params, images, labels)))
        print('epoch {}: loss {:.4f} acc {:.3f}'.format(
            epoch, np.mean(losses), np.mean(accs[-10:])))
    return params, float(np.mean(accs[-10:]))


if __name__ == '__main__':
    url = 'file://' + tempfile.mkdtemp() + '/mnist'
    generate_synthetic_mnist(url)
    train(url)
