"""Hello world: write a petastorm_tpu dataset, read it back three ways.

Reference analogue: ``examples/hello_world/petastorm_dataset/`` (generate +
python/tf read) and ``external_dataset/`` (plain parquet via make_batch_reader).
"""

import tempfile

import numpy as np

from petastorm_tpu import make_batch_reader, make_jax_loader, make_reader, \
    materialize_dataset
from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema('HelloWorldSchema', [
    UnischemaField('id', np.int32, (), ScalarCodec(), False),
    UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
])


def row_generator(x):
    rng = np.random.default_rng(x)
    return {'id': np.int32(x),
            'image1': rng.integers(0, 255, (128, 256, 3), dtype=np.uint8),
            'array_4d': rng.integers(0, 255, (4, 128, 30, 3), dtype=np.uint8)}


def generate_petastorm_tpu_dataset(output_url, rows_count=10):
    with materialize_dataset(output_url, HelloWorldSchema,
                             row_group_size_mb=256) as writer:
        writer.write_rows(row_generator(i) for i in range(rows_count))


def python_hello_world(dataset_url):
    with make_reader(dataset_url, num_epochs=1) as reader:
        for row in reader:
            print(row.id, row.image1.shape, row.array_4d.shape)


def jax_hello_world(dataset_url):
    with make_reader(dataset_url, num_epochs=1) as reader:
        loader = make_jax_loader(reader, batch_size=4,
                                 shuffling_queue_capacity=10)
        for batch in loader:
            print('batch of', len(batch['id']), 'images', batch['image1'].shape)


def external_dataset_hello_world(parquet_url):
    """Read any parquet store (no petastorm_tpu metadata) vectorized."""
    with make_batch_reader(parquet_url, num_epochs=1) as reader:
        for batch in reader:
            print('columns:', batch._fields, 'rows:', len(batch[0]))


if __name__ == '__main__':
    url = 'file://' + tempfile.mkdtemp() + '/hello_world'
    generate_petastorm_tpu_dataset(url)
    python_hello_world(url)
    jax_hello_world(url)
