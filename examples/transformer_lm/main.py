"""Long-context LM training: NGram windowed reader → sharded transformer.

This is the pipeline SURVEY §5.7 calls for: the NGram reader assembles
fixed-length timestamped token windows (data-side sequence assembly), the
JAX side trains a transformer LM whose parallelism (dp/sp/tp) is expressed
through GSPMD shardings — ring attention carries the sequence dimension when
the mesh has a 'seq' axis.
"""

import tempfile

import numpy as np

from petastorm_tpu import make_reader, materialize_dataset
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device
from petastorm_tpu.ngram import NGram
from petastorm_tpu.unischema import Unischema, UnischemaField

TokenSchema = Unischema('TokenSchema', [
    UnischemaField('step', np.int64, (), ScalarCodec(), False),
    UnischemaField('tokens', np.int32, (64,), NdarrayCodec(), False),
])


def generate_token_stream(output_url, n_steps=512, vocab=128, seed=0):
    """Each row is a 64-token chunk; consecutive rows continue the stream."""
    rng = np.random.default_rng(seed)
    with materialize_dataset(output_url, TokenSchema, rows_per_file=256,
                             row_group_size_mb=64) as w:
        w.write_rows({'step': np.int64(i),
                      'tokens': rng.integers(0, vocab, 64, dtype=np.int32)}
                     for i in range(n_steps))


def train(dataset_url, steps=20, mesh=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from petastorm_tpu.models import transformer_lm as tlm

    # window of 2 consecutive chunks -> (input window, continuation window)
    ngram = NGram(fields={0: ['step', 'tokens'], 1: ['tokens']},
                  delta_threshold=1, timestamp_field='step')
    config = tlm.TransformerConfig(
        vocab_size=128, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
        attention='ring' if mesh is not None and 'seq' in mesh.axis_names
        else 'blockwise')
    params = tlm.init(jax.random.PRNGKey(0), config)
    if mesh is not None:
        pshard = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), tlm.param_specs(config, mesh),
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        params = jax.tree_util.tree_map(jax.device_put, params, pshard)
    optimizer, step_fn = tlm.make_train_step(config, mesh)
    opt_state = optimizer.init(params)

    losses = []
    with make_reader(dataset_url, schema_fields=ngram, num_epochs=None,
                     shuffle_row_groups=False) as reader:
        # NGram windows batch through the JAX loader with per-timestep
        # collation: a batch is {offset: {field: (B, ...) array}}, staged to
        # the device by the prefetch pipeline
        loader = JaxDataLoader(reader, batch_size=8, drop_last=True)
        for batch in prefetch_to_device(iter(loader), size=2):
            tokens = batch[0]['tokens']
            # next-token targets: shift within the window, next chunk's first
            # token closes the gap — exact continuation thanks to NGram
            nxt = batch[1]['tokens'][:, 0]
            targets = jnp.concatenate([tokens[:, 1:], nxt[:, None]], axis=1)
            if mesh is not None:
                bshard = NamedSharding(mesh, tlm.batch_spec(mesh))
                tokens = jax.device_put(tokens, bshard)
                targets = jax.device_put(targets, bshard)
            params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
            losses.append(float(loss))
            if len(losses) >= steps:
                break
    print('first loss {:.3f} -> last loss {:.3f}'.format(losses[0], losses[-1]))
    return losses, params, config


def sample(params, config, prompt_len=8, max_new_tokens=32, temperature=0.8,
           top_p=0.9, seed=0):
    """Continue a prompt with the trained model (KV-cache decode, nucleus
    sampling). Returns the sampled (1, max_new_tokens) continuation."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.models import transformer_lm as tlm

    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(0, config.vocab_size, (1, prompt_len)), jnp.int32)
    out = tlm.generate(params, prompt, config, max_new_tokens,
                       temperature=temperature, top_p=top_p,
                       rng=jax.random.PRNGKey(seed))
    print('prompt {} -> continuation {}'.format(
        np.asarray(prompt)[0][:8], np.asarray(out)[0][:8]))
    return out


if __name__ == '__main__':
    url = 'file://' + tempfile.mkdtemp() + '/tokens'
    generate_token_stream(url)
    _, params, config = train(url)
    sample(params, config)
