"""Train the compact residual CNN on an ImageNet-style petastorm_tpu dataset.

End-to-end image pipeline (the decode-heavy regime where infeed stalls live):
``make_columnar_reader`` decodes png/jpeg bytes on the worker pool, a
``TransformSpec`` resizes variable-shape images to a fixed crop **in the
workers** (cv2 releases the GIL), ``JaxDataLoader`` assembles uint8 column
batches, ``prefetch_to_device`` overlaps host→HBM staging with compute, and
normalization runs fused inside the jitted train step.

Reference analogue: the reference stops at writing the dataset
(``examples/imagenet/generate_petastorm_imagenet.py``); it has no training
loop. The schema/ETL parity lives in ``schema.py`` / ``generate_imagenet.py``.

Usage::

    python -m examples.imagenet.main --dataset-url file:///tmp/imagenet_pq \
        --batch-size 64 --steps 100
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))

IMAGE_SIZE = 224


def make_resize_transform(size: int = IMAGE_SIZE):
    """Columnar TransformSpec: ragged (H, W, 3) images -> (size, size, 3)."""
    from petastorm_tpu.transform import TransformSpec

    def resize_batch(columns):
        import cv2
        images = columns['image']
        out = np.empty((len(images), size, size, 3), dtype=np.uint8)
        for i, img in enumerate(images):
            out[i] = cv2.resize(img, (size, size), interpolation=cv2.INTER_AREA)
        columns['image'] = out
        return columns

    return TransformSpec(
        resize_batch,
        edit_fields=[('image', np.uint8, (size, size, 3), False)],
        selected_fields=['image', 'label'])


def train(dataset_url: str, batch_size: int = 64, steps: int = 100,
          workers_count: int = None, num_classes: int = 16,
          lr: float = 1e-3, log_every: int = 20,
          image_size: int = IMAGE_SIZE):
    import jax

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device
    from petastorm_tpu.models import image_cnn

    params = image_cnn.init(jax.random.PRNGKey(0), num_classes=num_classes)
    step_fn = image_cnn.make_train_step(lr=lr)

    workers = workers_count or min(8, max(2, os.cpu_count() or 2))
    done = 0
    with make_columnar_reader(dataset_url, num_epochs=None,
                              reader_pool_type='thread', workers_count=workers,
                              transform_spec=make_resize_transform(image_size)
                              ) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        start = time.perf_counter()
        for batch in prefetch_to_device(iter(loader), size=4):
            params, loss = step_fn(params, batch['image'], batch['label'])
            done += 1
            if done % log_every == 0 or done == steps:
                jax.block_until_ready(loss)
                rate = done * batch_size / (time.perf_counter() - start)
                print('step {:4d}  loss {:.4f}  {:.1f} images/sec'.format(
                    done, float(loss), rate))
            if done >= steps:
                break
    return params


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--dataset-url', type=str, required=True)
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--workers', type=int, default=None)
    parser.add_argument('--num-classes', type=int, default=16)
    parser.add_argument('--image-size', type=int, default=IMAGE_SIZE)
    args = parser.parse_args(argv)
    train(args.dataset_url, batch_size=args.batch_size, steps=args.steps,
          workers_count=args.workers, num_classes=args.num_classes,
          image_size=args.image_size)


if __name__ == '__main__':
    main()
