"""ImageNet dataset schema (reference parity:
``/root/reference/examples/imagenet/schema.py:21-25`` — noun_id, text, and a
variable-shaped png-compressed RGB image).

The reference ETL re-encodes everything to png; real ImageNet source files
are jpeg, where DCT-scaled decode (``decode_hints={'image': {'scale': 2}}``)
pays — :func:`make_imagenet_schema` selects the codec."""

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField


def make_imagenet_schema(image_codec: str = 'png') -> Unischema:
    """ImageNet schema with the image stored as ``image_codec`` ('png' keeps
    reference parity and is lossless; 'jpeg' matches real ImageNet files and
    enables DCT-scaled decode)."""
    return Unischema('ImagenetSchema', [
        UnischemaField('noun_id', str, (), ScalarCodec(), False),
        UnischemaField('text', str, (), ScalarCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (None, None, 3),
                       CompressedImageCodec(image_codec), False),
    ])


ImagenetSchema = make_imagenet_schema('png')
