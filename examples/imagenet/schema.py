"""ImageNet dataset schema (reference parity:
``/root/reference/examples/imagenet/schema.py:21-25`` — noun_id, text, and a
variable-shaped png-compressed RGB image)."""

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema('ImagenetSchema', [
    UnischemaField('noun_id', str, (), ScalarCodec(), False),
    UnischemaField('text', str, (), ScalarCodec(), False),
    UnischemaField('label', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
])
