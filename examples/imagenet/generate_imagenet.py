"""Convert an ImageNet directory tree into a petastorm_tpu dataset.

TPU-first re-design of the reference ETL
(``/root/reference/examples/imagenet/generate_petastorm_imagenet.py:1-115``):
the reference runs a Spark job per noun directory; here the pyarrow-native
writer streams rows directly — no cluster needed — and a ``--synthetic`` mode
generates realistic-size images so the decode-heavy pipeline can be exercised
(and benchmarked) without the real dataset.

Expected layout: ``<input>/<noun_id>/*.JPEG`` (noun_id like ``n01440764``).

Usage::

    python -m examples.imagenet.generate_imagenet -i /data/imagenet -o file:///tmp/imagenet_pq
    python -m examples.imagenet.generate_imagenet --synthetic 512 -o file:///tmp/imagenet_pq
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                '..', '..'))

from examples.imagenet.schema import make_imagenet_schema  # noqa: E402
from petastorm_tpu.etl.dataset_metadata import materialize_dataset  # noqa: E402


def rows_from_directory(input_path: str, limit: int = None):
    """Yield schema rows from an ImageNet-layout directory tree."""
    import cv2
    noun_dirs = sorted(d for d in glob.glob(os.path.join(input_path, 'n*'))
                       if os.path.isdir(d))
    if not noun_dirs:
        raise ValueError('No noun directories (n*) under {}'.format(input_path))
    count = 0
    for label, noun_dir in enumerate(noun_dirs):
        noun_id = os.path.basename(noun_dir)
        for image_path in sorted(glob.glob(os.path.join(noun_dir, '*'))):
            bgr = cv2.imread(image_path, cv2.IMREAD_COLOR)
            if bgr is None:
                continue
            yield {'noun_id': noun_id, 'text': noun_id,
                   'label': np.int64(label),
                   'image': np.ascontiguousarray(bgr[:, :, ::-1])}  # BGR->RGB
            count += 1
            if limit is not None and count >= limit:
                return


def synthetic_rows(n: int, classes: int = 16, seed: int = 0,
                   base_hw=(375, 500)):
    """Realistic-size, photo-like random images (the reference's ImageNet
    median is about 500x375); shapes jitter so the variable-shape path is
    exercised.

    Content is a low-frequency random field plus mild sensor-like noise, not
    uniform noise: image codec cost tracks the entropy-coded byte count, and
    real photos compress to tens of KB at these sizes while uniform noise is
    incompressible — noise images overstate decode cost ~2.5x and bury the
    DCT-scaled decode path (``decode_hints``) this dataset exists to
    exercise."""
    import cv2
    rng = np.random.default_rng(seed)
    for i in range(n):
        h = int(base_hw[0] * rng.uniform(0.8, 1.2))
        w = int(base_hw[1] * rng.uniform(0.8, 1.2))
        label = i % classes
        small = rng.integers(0, 255, size=(24, 32, 3), dtype=np.uint8)
        img = cv2.resize(small, (w, h), interpolation=cv2.INTER_CUBIC)
        img = np.clip(img.astype(np.int16)
                      + rng.integers(-8, 8, size=img.shape),
                      0, 255).astype(np.uint8)
        yield {'noun_id': 'n{:08d}'.format(label), 'text': 'class {}'.format(label),
               'label': np.int64(label),
               'image': img}


def generate(output_url: str, rows, row_group_size_mb: float = 32.0,
             image_codec: str = 'png') -> int:
    written = 0

    def counting():
        nonlocal written
        for row in rows:
            written += 1
            yield row

    with materialize_dataset(output_url, make_imagenet_schema(image_codec),
                             row_group_size_mb=row_group_size_mb) as writer:
        writer.write_rows(counting())
    return written


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('-i', '--input-path', type=str, default=None)
    parser.add_argument('-o', '--output-url', type=str, required=True)
    parser.add_argument('--limit', type=int, default=None,
                        help='stop after this many images')
    parser.add_argument('--synthetic', type=int, default=None,
                        help='generate N synthetic images instead of reading '
                             '--input-path')
    parser.add_argument('--row-group-size-mb', type=float, default=32.0)
    parser.add_argument('--image-codec', type=str, default='png',
                        choices=('png', 'jpeg'),
                        help='stored image codec (jpeg matches real ImageNet '
                             'files and enables DCT-scaled decode hints)')
    args = parser.parse_args(argv)

    if (args.synthetic is None) == (args.input_path is None):
        parser.error('exactly one of --input-path / --synthetic is required')
    rows = (synthetic_rows(args.synthetic) if args.synthetic is not None
            else rows_from_directory(args.input_path, args.limit))
    n = generate(args.output_url, rows, args.row_group_size_mb,
                 image_codec=args.image_codec)
    print('wrote {} rows to {}'.format(n, args.output_url))


if __name__ == '__main__':
    main()
