"""PyTorch adapter (reference parity: ``petastorm/pytorch.py``).

``DataLoader`` (row-granular readers) and ``BatchedDataLoader`` (vectorized
readers) yield dicts of ``torch.Tensor`` batches. The batched loader keeps
columns vectorized end-to-end through the numpy shuffling buffers and converts
to torch zero-copy at the edge (``torch.as_tensor`` shares memory with the
numpy batch), which is the same optimization the reference implements with
torch-native buffers (``pytorch.py:259-425``).
"""

from __future__ import annotations

import logging
from decimal import Decimal

import numpy as np

from petastorm_tpu.readers.shuffling_buffer import (
    BatchedNoopShufflingBuffer, BatchedRandomShufflingBuffer,
    NoopShufflingBuffer, RandomShufflingBuffer)

logger = logging.getLogger(__name__)


def _sanitize_pytorch_types(row_as_dict):
    """In-place torch-compatible casts (reference ``pytorch.py:41-71``):
    bool→uint8, uint16→int32, uint32→int64, Decimal→float64; None values are
    rejected (use TransformSpec to fill nulls)."""
    for name, value in row_as_dict.items():
        if value is None:
            raise TypeError(
                'Field {} is None. Use a TransformSpec to fill nulls before '
                'the torch loader'.format(name))
        if isinstance(value, Decimal):
            row_as_dict[name] = float(value)
            continue
        arr = np.asarray(value)
        if arr.dtype == np.bool_:
            row_as_dict[name] = arr.astype(np.uint8)
        elif arr.dtype == np.uint16:
            row_as_dict[name] = arr.astype(np.int32)
        elif arr.dtype == np.uint32:
            row_as_dict[name] = arr.astype(np.int64)
        elif arr.dtype.kind == 'O' and arr.size and isinstance(arr.flat[0], Decimal):
            row_as_dict[name] = arr.astype(np.float64)
        else:
            row_as_dict[name] = arr
    return row_as_dict


def decimal_friendly_collate(batch_rows):
    """Stack a list of sanitized row dicts into a dict of torch tensors
    (reference ``decimal_friendly_collate``, ``pytorch.py:74-96``); string and
    ragged fields are returned as python lists."""
    import torch
    out = {}
    for key in batch_rows[0]:
        vals = [r[key] for r in batch_rows]
        arrs = [np.asarray(v) for v in vals]
        shapes = {a.shape for a in arrs}
        kinds = {a.dtype.kind for a in arrs}
        if len(shapes) == 1 and not (kinds & {'U', 'S', 'O'}):
            out[key] = torch.as_tensor(np.stack(arrs))
        else:
            out[key] = vals
    return out


class LoaderBase(object):
    """Iteration-state guard + auto-reset (reference ``pytorch.py:104-129``)."""

    def __init__(self, reader):
        self.reader = reader
        self._in_iter = None
        self._error = None

    def __iter__(self):
        if self._error is not None:
            raise RuntimeError('Cannot start a new iteration after a failed one') \
                from self._error
        if self._in_iter is not None and self._in_iter:
            raise RuntimeError('Loader is already being iterated')
        if self._in_iter is not None and not self._cache_hot():
            self.reader.reset()
            logger.warning('Start a new pass of the Reader. To avoid I/O, pass '
                           'inmemory_cache_all=True')
        self._in_iter = True
        try:
            for batch in self._iter_impl():
                yield batch
        except Exception as e:
            self._error = e
            raise
        finally:
            self._in_iter = False

    def _iter_impl(self):
        raise NotImplementedError

    def _cache_hot(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.reader.stop()
        self.reader.join()


class DataLoader(LoaderBase):
    """Row-granular loader: per-row shuffling buffer → collate
    (reference ``pytorch.py:132-256``)."""

    def __init__(self, reader, batch_size=1, collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, seed=None):
        super(DataLoader, self).__init__(reader)
        if getattr(reader, 'ngram', None) is not None:
            raise NotImplementedError('NGram readers are not supported by the '
                                      'torch DataLoader')
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self.seed = seed

    def _iter_impl(self):
        if self.shuffling_queue_capacity > 0:
            buffer = RandomShufflingBuffer(
                self.shuffling_queue_capacity,
                min_after_retrieve=max(1, self.shuffling_queue_capacity - 1),
                seed=self.seed)
        else:
            buffer = NoopShufflingBuffer()
        rows = []

        def drain(final):
            while buffer.can_retrieve():
                rows.append(buffer.retrieve())
                if len(rows) == self.batch_size:
                    yield self.collate_fn(rows)
                    rows.clear()
            if final and rows:
                yield self.collate_fn(rows)
                rows.clear()

        for row in self.reader:
            if self.reader.batched_output:
                # transpose column batch into rows (reference :204-216)
                cols = row._asdict() if hasattr(row, '_asdict') else dict(row)
                n = len(next(iter(cols.values())))
                for i in range(n):
                    while not buffer.can_add():
                        for b in drain(False):
                            yield b
                    buffer.add_many([_sanitize_pytorch_types(
                        {k: v[i] for k, v in cols.items()})])
            else:
                while not buffer.can_add():
                    for b in drain(False):
                        yield b
                buffer.add_many([_sanitize_pytorch_types(
                    row._asdict() if hasattr(row, '_asdict') else dict(row))])
            for b in drain(False):
                yield b
        buffer.finish()
        for b in drain(True):
            yield b


class BatchedDataLoader(LoaderBase):
    """Vectorized loader for batched readers; optional in-memory cache replays
    epoch-1 tensors for epochs 2..N (reference ``pytorch.py:259-425``).

    :param transform_fn: applied to the dict of numpy column batches before
        tensor conversion (default: ``torch.as_tensor`` per column).
    """

    def __init__(self, reader, batch_size=1, transform_fn=None,
                 shuffling_queue_capacity=0, seed=None,
                 inmemory_cache_all=False):
        super(BatchedDataLoader, self).__init__(reader)
        if getattr(reader, 'ngram', None) is not None:
            raise NotImplementedError('NGram readers are not supported by the '
                                      'torch BatchedDataLoader')
        if not reader.batched_output:
            raise ValueError('BatchedDataLoader requires a batched reader '
                             '(make_batch_reader); use DataLoader for row readers')
        self.batch_size = batch_size
        self.transform_fn = transform_fn
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self.seed = seed
        self.inmemory_cache_all = inmemory_cache_all
        self._cache = [] if inmemory_cache_all else None
        self._cache_complete = False

    def _cache_hot(self):
        return self._cache_complete

    def _to_torch(self, batch):
        import torch
        if self.transform_fn is not None:
            batch = self.transform_fn(batch)
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if arr.dtype.kind in ('U', 'S', 'O'):
                out[k] = v
            else:
                # torch cannot represent non-writable tensors; arrow's
                # zero-copy numpy views are read-only, so copy at the boundary
                if not arr.flags.writeable:
                    arr = arr.copy()
                out[k] = torch.as_tensor(arr)
        return out

    def _iter_impl(self):
        if self._cache_complete:
            for batch in self._cache:
                yield batch
            return
        if self._cache is not None:
            self._cache = []
        if self.shuffling_queue_capacity > 0:
            buffer = BatchedRandomShufflingBuffer(
                self.shuffling_queue_capacity + self.batch_size,
                min_after_retrieve=max(1, self.shuffling_queue_capacity - self.batch_size),
                batch_size=self.batch_size, seed=self.seed)
        else:
            buffer = BatchedNoopShufflingBuffer(self.batch_size)

        def emit(columns):
            batch = self._to_torch(columns)
            if self._cache is not None:
                self._cache.append(batch)
            return batch

        for chunk in self.reader:
            cols = chunk._asdict() if hasattr(chunk, '_asdict') else dict(chunk)
            cols = _sanitize_pytorch_types(cols)
            # object/ragged columns cannot live in the vectorized buffer
            dense = {k: v for k, v in cols.items()
                     if np.asarray(v).dtype.kind not in ('U', 'S', 'O')}
            while not buffer.can_add():
                yield emit(buffer.retrieve())
            buffer.add_many(dense)
            while buffer.can_retrieve() and buffer.size >= self.batch_size:
                yield emit(buffer.retrieve())
        buffer.finish()
        while buffer.can_retrieve():
            yield emit(buffer.retrieve())
        if self._cache is not None:
            self._cache_complete = True
