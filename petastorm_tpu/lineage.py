"""Sample-level data lineage: batch provenance, epoch coverage auditing, and
bad-sample quarantine.

The performance layers (``ReaderStats``, spans, heartbeats — PRs 1–4) observe
*how fast* the pipeline moves; nothing observes *what data the model actually
saw*. A silent duplicate or drop — a dying worker, a skewed shard, an
off-by-one in shuffling — corrupts training invisibly, and a single corrupt
sample kills the reader with no record of which row did it. Because the
reader is row-group addressable end to end (every ventilated work item is one
``(file, row_group)`` piece), exact lineage is cheap to carry: one compact
record per *item*, never per row.

Four pieces:

- **Provenance records.** Every published item carries a
  :class:`Provenance` (dataset digest, file index + path, row-group ordinal,
  row-offset selection, epoch, shard, worker) attached at the worker and
  shipped in-band: thread/dummy pools wrap the payload in a
  :class:`LineageEnvelope`; the process pool rides the record in the
  ``DATA`` control frame (the accounting-message pattern — payload bytes
  stay zero-copy). The consumer-side :class:`LineageTracker` registers each
  record into a bounded ring and keeps per-epoch delivery ledgers.
- **Coverage auditing.** :class:`CoverageAuditor` asserts exactly-once row
  delivery per epoch per shard from the ventilated-vs-delivered ledgers:
  duplicates and drops are reported with their source row groups (the
  post-mortem a killed worker needs), row-exact coverage is checked against
  the row-group footers when every selection is transparent, and
  shuffle-quality (item shuffle-lag distribution; per-batch
  adjacent-source-run-length via :class:`BatchProvenance`) and inter-shard
  skew metrics quantify *how well* shuffled/balanced the delivery was.
- **Replay.** :func:`replay` re-fetches the exact rows of a recorded
  provenance through the same predicate/row-group machinery the original
  read used — bit-exact repro of a bad batch from its provenance alone.
- **Quarantine.** ``on_decode_error='raise'|'skip'|'quarantine'`` turns
  decode/transform exceptions into counted, provenance-tagged quarantine
  records (``rows_quarantined``/``items_quarantined`` in ``ReaderStats``,
  records on ``/coverage``, ``/diagnostics`` and in flight records) instead
  of a dead worker; the quarantined rows are dropped and the epoch
  completes.

Lineage is **on by default** and designed to measure within noise: one
namedtuple per row-group item on the worker side, one ring insert per item on
the consumer side, and per-row work only as one vectorized ``int64`` column
through the shuffling buffer (no per-row Python objects anywhere). Set
``PETASTORM_TPU_LINEAGE=0`` to compile every publication site out. See
``docs/lineage.md``.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: Environment variable gating lineage publication (default on).
#: ``0``/``false``/``off`` disable envelopes, ledgers and batch columns.
LINEAGE_ENV_VAR = 'PETASTORM_TPU_LINEAGE'

#: Synthetic int64 column the JAX loader threads through the shuffling
#: buffer: each row's packed ``(seq << PACK_SHIFT) | payload_offset``.
LINEAGE_COLUMN = '_lineage_src'

#: Key under which a finished loader batch exposes its
#: :class:`BatchProvenance` (next to the existing ``'_host'`` convention).
PROVENANCE_KEY = '_provenance'

#: Bits reserved for the payload-row offset in a packed source id. Row
#: groups are far below 16M rows, so ``seq`` keeps 39 effective bits.
PACK_SHIFT = 24
_OFFSET_MASK = (1 << PACK_SHIFT) - 1

#: Registered provenance records kept in the tracker's ring.
DEFAULT_RECORD_CAPACITY = 65536

#: Per-epoch ledgers kept before the oldest epoch is evicted (bounds
#: ``num_epochs=None`` streams).
DEFAULT_EPOCH_CAPACITY = 16

#: Quarantine records kept in the ring (totals keep counting past it).
DEFAULT_QUARANTINE_CAPACITY = 1024

#: Valid ``on_decode_error`` policies.
DECODE_ERROR_POLICIES = ('raise', 'skip', 'quarantine')

#: Exception classes that stay loud under EVERY ``on_decode_error`` policy —
#: they signal infrastructure failure (storage, memory, interpreter
#: shutdown), not a bad sample. Shared by the item-level quarantine gate and
#: the cell-level tolerant decode loop.
NEVER_QUARANTINE = (OSError, MemoryError, KeyboardInterrupt, SystemExit)


def lineage_enabled() -> bool:
    """The :data:`LINEAGE_ENV_VAR` gate (default on)."""
    value = os.environ.get(LINEAGE_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def validate_decode_error_policy(policy: str) -> str:
    if policy not in DECODE_ERROR_POLICIES:
        raise ValueError('on_decode_error must be one of {}, got {!r}'.format(
            DECODE_ERROR_POLICIES, policy))
    return policy


class Provenance(NamedTuple):
    """Compact per-item provenance: where the rows of one published result
    came from. Plain data end to end — pickles across the process-pool
    boundary in the control frame and JSON-ifies via :meth:`_asdict`.

    ``selection`` describes which source rows (file-order offsets within the
    row group) the payload carries:

    - ``('all', n)`` — all ``n`` rows, in file order.
    - ``('slice', lo, hi)`` — rows ``[lo, hi)`` (shuffle_row_drop partition).
    - ``('index', (o0, o1, ...))`` — explicit offsets (predicate matches,
      or a contiguous range with quarantined rows dropped).
    - ``('windows', n)`` — ``n`` NGram windows (window-, not row-granular).
    - ``('opaque', n)`` — ``n`` rows whose source offsets are unknowable
      (local-cache hit, or a transform that changed the row count).
    """
    dataset: str        # short dataset-path digest (12 hex chars)
    file_index: int     # ordinal of `path` among the reader's files
    path: str           # absolute path on the dataset filesystem
    row_group: int      # row-group ordinal within the file
    rows: int           # rows (or windows) this payload delivers
    selection: tuple
    epoch: int          # ventilation epoch the item belongs to
    shard: int          # reader shard (cur_shard), -1 when unsharded
    piece_index: int    # ventilation piece ordinal (the replay handle)
    partition: tuple    # shuffle_row_drop_partition (k, n)
    worker_id: int      # worker that produced the payload


class LineageEnvelope:
    """In-band carrier wrapping one published payload with its provenance
    (thread/dummy pools; the process pool moves the record in the control
    frame instead so payload frames stay zero-copy)."""

    __slots__ = ('payload', 'provenance')

    def __init__(self, payload, provenance: Provenance):
        self.payload = payload
        self.provenance = provenance


def batch_provenance_of(batch) -> Optional['BatchProvenance']:
    """The :class:`BatchProvenance` of a loader batch dict — top-level for
    host batches, under ``'_host'`` for staged/sharded ones (keeping every
    other top-level entry a ``jax.Array``). ``None`` when absent."""
    if not isinstance(batch, dict):
        return None
    value = batch.get(PROVENANCE_KEY)
    if value is None:
        value = (batch.get('_host') or {}).get(PROVENANCE_KEY) \
            if isinstance(batch.get('_host'), dict) else None
    return value if isinstance(value, BatchProvenance) else None


def unwrap_envelope(item, tracker: Optional['LineageTracker']):
    """``(payload, seq-or-None)`` of a pool result: envelopes are unwrapped
    and registered with ``tracker`` (when given), raw payloads pass through."""
    if isinstance(item, LineageEnvelope):
        seq = tracker.register(item.provenance) if tracker is not None else None
        return item.payload, seq
    return item, None


def pack_source(seq: int, offset: int) -> int:
    """One packed int64 source id for row ``offset`` of registered item
    ``seq``."""
    return (seq << PACK_SHIFT) | (offset & _OFFSET_MASK)


def pack_rows(seq: int, n: int) -> np.ndarray:
    """Packed source ids for all ``n`` payload rows of item ``seq`` — the
    vectorized per-chunk form (one numpy op, no per-row Python)."""
    return (seq << PACK_SHIFT) + np.arange(n, dtype=np.int64)


def unpack_source(packed: int) -> Tuple[int, int]:
    return int(packed) >> PACK_SHIFT, int(packed) & _OFFSET_MASK


def selection_offsets(selection: tuple) -> Optional[np.ndarray]:
    """Source row offsets a selection covers (``None`` when not
    row-transparent)."""
    kind = selection[0]
    if kind == 'all':
        return np.arange(selection[1], dtype=np.int64)
    if kind == 'slice':
        return np.arange(selection[1], selection[2], dtype=np.int64)
    if kind == 'index':
        return np.asarray(selection[1], dtype=np.int64)
    return None


class LineageTracker:
    """Consumer-side lineage ledger of one reader.

    Holds (all ring-bounded):

    - the provenance **record ring**: ``seq -> Provenance`` for every
      registered (delivered) item — what ``batch['_provenance']`` and
      :func:`replay` resolve against;
    - per-epoch **ventilation** and **delivery ledgers** keyed by
      ``(piece_index, partition)`` — what :class:`CoverageAuditor` compares;
    - the **quarantine ring** plus running totals.

    Thread-safe: the ventilator thread records ventilations, the consumer
    thread registers deliveries, pools push quarantines.
    """

    def __init__(self, enabled: bool = True, dataset_digest: str = '',
                 shard: int = -1,
                 pieces: Optional[List[Tuple[str, int, int]]] = None,
                 items: Optional[List[Tuple[int, tuple]]] = None,
                 row_filtered: bool = False,
                 record_capacity: int = DEFAULT_RECORD_CAPACITY,
                 epoch_capacity: int = DEFAULT_EPOCH_CAPACITY,
                 quarantine_capacity: int = DEFAULT_QUARANTINE_CAPACITY,
                 record_vent_ts: bool = False):
        self.enabled = enabled
        self.dataset_digest = dataset_digest
        self.shard = shard
        #: True when a predicate/filters legitimately drop rows — row
        #: coverage is then checked for duplicates only, never for misses.
        self.row_filtered = row_filtered
        #: ``piece_index -> (path, row_group, num_rows)`` — the audit's
        #: source-of-truth for row-exact coverage (num_rows from footers).
        self.pieces = {i: tuple(p) for i, p in enumerate(pieces or [])}
        #: The full per-epoch item universe ``[(piece_index, partition)]``.
        self.items = [(int(i), tuple(p)) for i, p in (items or [])]
        self._record_capacity = record_capacity
        self._epoch_capacity = epoch_capacity
        #: When set (the reader wires it iff the latency plane is on), each
        #: ventilation stamps a monotonic timestamp that :meth:`register`
        #: correlates to the delivered item's ``seq`` — the start anchor of
        #: the end-to-end batch-latency histogram (``docs/latency.md``).
        self._record_vent_ts = bool(enabled and record_vent_ts)
        self._lock = threading.Lock()
        self._records: 'collections.OrderedDict[int, Provenance]' = \
            collections.OrderedDict()
        self._vent_ts: 'collections.OrderedDict[int, float]' = \
            collections.OrderedDict()
        self._next_seq = 0
        # epoch -> {'ventilated': Counter, 'vent_order': [key],
        #           'delivered': {key: [Provenance]}, 'order': [key],
        #           'rows': int}
        self._epochs: 'collections.OrderedDict[int, dict]' = \
            collections.OrderedDict()
        self._quarantines: 'collections.deque' = collections.deque(
            maxlen=quarantine_capacity)
        self.quarantined_rows_total = 0
        self.quarantined_items_total = 0
        self.records_registered = 0
        self.passes = 0

    # -- ledgers ---------------------------------------------------------------

    def _epoch_entry(self, epoch: int) -> dict:
        entry = self._epochs.get(epoch)
        if entry is None:
            entry = {'ventilated': collections.Counter(), 'vent_order': [],
                     'delivered': {}, 'order': [], 'rows': 0,
                     'quarantined': collections.Counter(), 'vent_ts': {}}
            self._epochs[epoch] = entry
            while len(self._epochs) > self._epoch_capacity:
                self._epochs.popitem(last=False)
        return entry

    def record_ventilated(self, epoch: int, piece_index: int,
                          partition: tuple) -> None:
        """Called from the reader's ventilate wrapper: one work item was
        handed to the pool for ``epoch``."""
        if not self.enabled or piece_index is None:
            return
        key = (piece_index, tuple(partition or (0, 1)))
        with self._lock:
            entry = self._epoch_entry(epoch)
            entry['ventilated'][key] += 1
            entry['vent_order'].append(key)
            if self._record_vent_ts:
                # FIFO of dispatch timestamps per key: re-ventilations of the
                # same item (multi-epoch keys live in separate epoch entries)
                # consume in dispatch order at register() time
                entry['vent_ts'].setdefault(key, []).append(
                    time.perf_counter())

    def register(self, record: Provenance) -> int:
        """Register one delivered item's provenance; returns its ``seq``
        (the handle packed into batch source ids)."""
        key = (record.piece_index, tuple(record.partition))
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._records[seq] = record
            while len(self._records) > self._record_capacity:
                self._records.popitem(last=False)
            entry = self._epoch_entry(record.epoch)
            if self._record_vent_ts:
                ts_fifo = entry['vent_ts'].get(key)
                if ts_fifo:
                    self._vent_ts[seq] = ts_fifo.pop(0)
                    while len(self._vent_ts) > self._record_capacity:
                        self._vent_ts.popitem(last=False)
            entry['delivered'].setdefault(key, []).append(record)
            entry['order'].append(key)
            entry['rows'] += record.rows
            self.records_registered += 1
        return seq

    def resolve(self, seq) -> Optional[Provenance]:
        """The provenance registered as ``seq`` (``None`` if ring-evicted)."""
        if seq is None:
            return None
        with self._lock:
            return self._records.get(int(seq))

    def ventilated_ts(self, seq) -> Optional[float]:
        """Monotonic dispatch timestamp of the item registered as ``seq``
        (``None`` when vent-ts tracking is off, the record was ring-evicted,
        or the ventilation predated the tracker)."""
        if seq is None:
            return None
        with self._lock:
            return self._vent_ts.get(int(seq))

    def add_quarantines(self, records) -> None:
        """Absorb quarantine records shipped back by a pool."""
        if not records:
            return
        with self._lock:
            for record in records:
                self._quarantines.append(record)
                rows = int(record.get('rows', 1))
                self.quarantined_rows_total += rows
                self.quarantined_items_total += 1
                epoch = record.get('epoch')
                if epoch is not None:
                    key = (record.get('piece_index', -1),
                           tuple(record.get('partition') or (0, 1)))
                    self._epoch_entry(int(epoch))['quarantined'][key] += rows

    def quarantines(self, limit: Optional[int] = None) -> List[dict]:
        """The most recent quarantine records (ring-bounded)."""
        with self._lock:
            records = list(self._quarantines)
        return records[-limit:] if limit else records

    def delivery_deficit(self, epoch: int, piece_index: int,
                         partition: tuple) -> Optional[int]:
        """Ventilated-minus-accounted count for one item key in one epoch —
        the pools' **exactly-once redispatch guard**: after a worker crash,
        an outstanding item whose deficit is already ``<= 0`` was delivered
        (or quarantined) before the accounting message died with the worker,
        and must NOT be re-ventilated (that is the dup the auditor would
        catch). ``None`` when lineage is off or the epoch is unknown —
        callers then redispatch unconditionally (at-least-once degrade,
        documented in ``docs/robustness.md``)."""
        if not self.enabled or piece_index is None:
            return None
        key = (int(piece_index), tuple(partition or (0, 1)))
        with self._lock:
            entry = self._epochs.get(int(epoch))
            if entry is None:
                return None
            accounted = len(entry['delivered'].get(key, ()))
            if entry['quarantined'].get(key):
                accounted += 1
            return entry['ventilated'].get(key, 0) - accounted

    def start_pass(self) -> None:
        """Mark a ``Reader.reset()`` boundary. Epoch numbers are globally
        monotone across passes (the ventilator never rewinds its epoch
        counter), so every pass audits against fresh per-epoch ledgers —
        this only records that a new pass began."""
        with self._lock:
            self.passes += 1

    # -- views -----------------------------------------------------------------

    def epochs(self) -> List[int]:
        with self._lock:
            return sorted(self._epochs)

    def epoch_ledger(self, epoch: int) -> Optional[dict]:
        """A point-in-time deep-enough copy of one epoch's ledgers."""
        with self._lock:
            entry = self._epochs.get(epoch)
            if entry is None:
                return None
            return {'ventilated': dict(entry['ventilated']),
                    'vent_order': list(entry['vent_order']),
                    'delivered': {k: list(v)
                                  for k, v in entry['delivered'].items()},
                    'order': list(entry['order']),
                    'rows': entry['rows'],
                    'quarantined': dict(entry['quarantined'])}

    def coverage_report(self) -> dict:
        """The full :class:`CoverageAuditor` report (the ``/coverage``
        debug-endpoint payload)."""
        return CoverageAuditor(self).report()

    def flight_summary(self, quarantine_limit: int = 20) -> dict:
        """The condensed lineage section embedded in flight records."""
        report = self.coverage_report()
        report['recent_quarantines'] = self.quarantines(quarantine_limit)
        return report


class CoverageAuditor:
    """Audits a :class:`LineageTracker`'s ledgers: exactly-once delivery per
    epoch per shard, with duplicates/drops named by source row group, plus
    shuffle-quality and inter-shard skew metrics."""

    def __init__(self, tracker: LineageTracker):
        self._tracker = tracker

    def _piece_brief(self, piece_index: int, partition: tuple) -> dict:
        info = self._tracker.pieces.get(piece_index)
        brief = {'piece_index': piece_index, 'partition': list(partition)}
        if info is not None:
            brief.update({'path': info[0], 'row_group': info[1],
                          'num_rows': info[2]})
        return brief

    def audit_epoch(self, epoch: int) -> Optional[dict]:
        """One epoch's verdict: item-exactness (delivered == ventilated,
        dups/drops named), row-exactness (union of selections + quarantined
        offsets covers each row group exactly once — checked only when every
        selection is row-transparent), and the shuffle-lag distribution."""
        ledger = self._tracker.epoch_ledger(epoch)
        if ledger is None:
            return None
        ventilated = ledger['ventilated']
        delivered = ledger['delivered']
        quarantined = ledger['quarantined']
        dup_items, dropped_items, quarantined_items = [], [], []
        for key, count in sorted(ventilated.items()):
            got = len(delivered.get(key, ()))
            if got > count:
                dup_items.append(dict(self._piece_brief(*key),
                                      ventilated=count, delivered=got))
            elif got < count:
                if quarantined.get(key):
                    # every row of the item was quarantined/skipped: the
                    # item is accounted for, not silently dropped
                    quarantined_items.append(dict(
                        self._piece_brief(*key), ventilated=count,
                        delivered=got,
                        rows_quarantined=int(quarantined[key])))
                else:
                    dropped_items.append(dict(self._piece_brief(*key),
                                              ventilated=count, delivered=got))
        for key in sorted(set(delivered) - set(ventilated)):
            dup_items.append(dict(self._piece_brief(*key), ventilated=0,
                                  delivered=len(delivered[key])))

        # -- row-exactness: per piece, the union of delivered selections
        # plus quarantined rows must cover [0, num_rows) exactly once
        row_exact = True
        row_dups = row_missing = 0
        check_missing = not self._tracker.row_filtered
        by_piece: Dict[int, List] = {}
        for (piece_index, _partition), records in delivered.items():
            by_piece.setdefault(piece_index, []).extend(records)
        for piece_index, records in by_piece.items():
            info = self._tracker.pieces.get(piece_index)
            num_rows = info[2] if info else -1
            sels = [selection_offsets(r.selection) for r in records]
            if any(s is None for s in sels):
                row_exact = False
                continue
            covered = (np.concatenate(sels) if sels
                       else np.empty(0, np.int64))
            unique = np.unique(covered)
            row_dups += int(len(covered) - len(unique))
            if check_missing and num_rows is not None and num_rows >= 0:
                q_rows = sum(n for (pi, _p), n in quarantined.items()
                             if pi == piece_index)
                row_missing += max(0, int(num_rows - len(unique) - q_rows))
            elif check_missing:
                row_exact = False
        if not check_missing:
            row_exact = False

        lags = self._shuffle_lags(ledger)
        out = {
            'epoch': epoch,
            'items_expected': len(self._tracker.items) or None,
            'items_ventilated': sum(ventilated.values()),
            'items_delivered': sum(len(v) for v in delivered.values()),
            'rows_delivered': ledger['rows'],
            'rows_quarantined': int(sum(quarantined.values())),
            'dup_items': dup_items,
            'dropped_items': dropped_items,
            'quarantined_items': quarantined_items,
            'row_exact': row_exact,
            'row_dups': row_dups,
            'row_missing': row_missing,
            'complete': (not dup_items and not dropped_items
                         and row_dups == 0
                         and (not row_exact or row_missing == 0)),
            'shuffle': lags,
        }
        return out

    @staticmethod
    def _shuffle_lags(ledger: dict) -> dict:
        """Item-level shuffle quality: |arrival position - ventilation
        position| per item (lag), plus run lengths of consecutive arrivals
        from the same source file-piece."""
        vent_pos = {}
        for pos, key in enumerate(ledger['vent_order']):
            vent_pos.setdefault(key, []).append(pos)
        lags = []
        taken: Dict[tuple, int] = {}
        for pos, key in enumerate(ledger['order']):
            positions = vent_pos.get(key)
            if not positions:
                continue
            i = min(taken.get(key, 0), len(positions) - 1)
            taken[key] = i + 1
            lags.append(abs(pos - positions[i]))
        runs, current = [], 0
        last_piece = None
        for key in ledger['order']:
            if key[0] == last_piece:
                current += 1
            else:
                if current:
                    runs.append(current)
                current = 1
                last_piece = key[0]
        if current:
            runs.append(current)
        if not lags:
            return {'items': 0}
        lags_arr = np.asarray(lags)
        runs_arr = np.asarray(runs) if runs else np.asarray([0])
        return {
            'items': len(lags),
            'lag_mean': round(float(lags_arr.mean()), 3),
            'lag_p50': int(np.median(lags_arr)),
            'lag_max': int(lags_arr.max()),
            'adjacent_source_runs': len(runs),
            'run_length_mean': round(float(runs_arr.mean()), 3),
            'run_length_max': int(runs_arr.max()),
        }

    def report(self) -> dict:
        """The full audit: per-epoch verdicts plus totals. ``complete`` is
        the AND over audited epochs (an epoch still in flight reads as
        incomplete until its last item is delivered — audit after
        consumption)."""
        tracker = self._tracker
        epochs = {}
        for epoch in tracker.epochs():
            verdict = self.audit_epoch(epoch)
            if verdict is not None:
                epochs[epoch] = verdict
        return {
            'enabled': tracker.enabled,
            'dataset': tracker.dataset_digest,
            'shard': tracker.shard,
            'passes': tracker.passes,
            'records_registered': tracker.records_registered,
            'rows_quarantined_total': tracker.quarantined_rows_total,
            'items_quarantined_total': tracker.quarantined_items_total,
            'epochs': epochs,
            'complete': all(v['complete'] for v in epochs.values())
            if epochs else None,
        }

    def assert_complete(self) -> dict:
        """Raise ``AssertionError`` (naming the offending row groups) unless
        every audited epoch delivered exactly once; returns the report."""
        report = self.report()
        problems = []
        for epoch, verdict in report['epochs'].items():
            if verdict['dropped_items']:
                problems.append('epoch {}: dropped {}'.format(
                    epoch, verdict['dropped_items']))
            if verdict['dup_items']:
                problems.append('epoch {}: duplicated {}'.format(
                    epoch, verdict['dup_items']))
            if verdict['row_exact'] and (verdict['row_dups']
                                         or verdict['row_missing']):
                problems.append('epoch {}: {} duplicate / {} missing rows'
                                .format(epoch, verdict['row_dups'],
                                        verdict['row_missing']))
        if problems:
            raise AssertionError('coverage audit failed: ' +
                                 '; '.join(problems))
        return report

    @staticmethod
    def shard_skew(reports: List[dict]) -> dict:
        """Inter-shard skew across per-shard coverage reports (one reader
        per shard): rows delivered per shard per epoch and the max/min
        imbalance ratio."""
        per_shard = {}
        epochs = set()
        for report in reports:
            shard = report.get('shard', -1)
            rows = {int(e): v['rows_delivered']
                    for e, v in report.get('epochs', {}).items()}
            per_shard[shard] = rows
            epochs.update(rows)
        skew = {}
        for epoch in sorted(epochs):
            rows = [per_shard[s].get(epoch, 0) for s in sorted(per_shard)]
            low = min(rows)
            skew[epoch] = {
                'rows_per_shard': {s: per_shard[s].get(epoch, 0)
                                   for s in sorted(per_shard)},
                'skew_ratio': round(max(rows) / low, 4) if low else None,
            }
        return {'shards': sorted(per_shard), 'epochs': skew}


class BatchProvenance:
    """Row-level provenance of one assembled loader batch.

    Wraps the packed int64 source column that rode through the shuffling
    buffer: row ``i`` of the batch came from payload offset
    ``sources[i] & OFFSET_MASK`` of registered item ``sources[i] >> SHIFT``.
    Resolution back to :class:`Provenance` records is lazy (the hot path
    never touches Python objects per row)."""

    __slots__ = ('sources', '_tracker')

    def __init__(self, sources: np.ndarray, tracker: Optional[LineageTracker]):
        self.sources = np.asarray(sources, dtype=np.int64)
        self._tracker = tracker

    def __len__(self) -> int:
        return len(self.sources)

    def seqs(self) -> np.ndarray:
        return self.sources >> PACK_SHIFT

    def offsets(self) -> np.ndarray:
        return self.sources & _OFFSET_MASK

    def record_for_row(self, i: int) -> Optional[Provenance]:
        if self._tracker is None:
            return None
        return self._tracker.resolve(int(self.sources[i]) >> PACK_SHIFT)

    def records(self) -> Dict[int, Optional[Provenance]]:
        """``seq -> Provenance`` for every distinct source item in the batch
        (``None`` values mark ring-evicted records)."""
        out = {}
        if self._tracker is None:
            return out
        for seq in np.unique(self.seqs()):
            out[int(seq)] = self._tracker.resolve(int(seq))
        return out

    def shuffle_quality(self) -> dict:
        """Row-level shuffle quality of this batch: adjacent-source run
        lengths (runs of consecutive rows from the same source item — long
        runs mean the shuffle buffer is too small to decorrelate row-group
        order) and distinct-source count."""
        seqs = self.seqs()
        if not len(seqs):
            return {'rows': 0}
        boundaries = np.flatnonzero(np.diff(seqs) != 0)
        run_lengths = np.diff(np.concatenate(
            ([0], boundaries + 1, [len(seqs)])))
        return {
            'rows': int(len(seqs)),
            'sources': int(len(np.unique(seqs))),
            'adjacent_source_runs': int(len(run_lengths)),
            'run_length_mean': round(float(run_lengths.mean()), 3),
            'run_length_max': int(run_lengths.max()),
        }

    def summary(self) -> dict:
        """JSON-able description: per-source row counts with their resolved
        provenance — the human-readable answer to "where did this batch's
        rows come from"."""
        seqs = self.seqs()
        sources = []
        for seq, count in zip(*np.unique(seqs, return_counts=True)):
            record = (self._tracker.resolve(int(seq))
                      if self._tracker is not None else None)
            entry = {'seq': int(seq), 'rows': int(count)}
            if record is not None:
                entry.update({'path': record.path,
                              'row_group': record.row_group,
                              'epoch': record.epoch,
                              'shard': record.shard,
                              'selection': list(record.selection[:1]) +
                              [int(x) if isinstance(x, (int, np.integer))
                               else list(x) for x in record.selection[1:]]})
            else:
                entry['evicted'] = True
            sources.append(entry)
        return {'rows': int(len(seqs)), 'sources': sources,
                'shuffle': self.shuffle_quality()}


# -- quarantine records -------------------------------------------------------

def make_quarantine_record(piece, piece_index: int, epoch: int,
                           partition: tuple, shard: int, stage: str,
                           error: BaseException, field: Optional[str] = None,
                           rows: int = 1,
                           row_offsets=None) -> dict:
    """One JSON-able quarantine record (what pools ship back and the tracker
    rings)."""
    record = {
        'stage': stage,
        'error': '{}: {}'.format(type(error).__name__, error)[:500],
        'path': piece.path,
        'row_group': piece.row_group,
        'piece_index': piece_index,
        'epoch': epoch,
        'partition': list(partition),
        'shard': shard,
        'rows': int(rows),
        # deliberate wall clock: quarantine records are human-facing
        # evidence ("when did the bad sample appear"), never aged
        'ts': time.time(),  # petalint: disable=monotonic-clock
    }
    if field is not None:
        record['field'] = field
    if row_offsets is not None:
        record['row_offsets'] = [int(o) for o in row_offsets]
    return record


def crash_quarantine_record(tracker: LineageTracker, piece_index: int,
                            epoch: int, partition: tuple,
                            crash_count: int) -> dict:
    """Quarantine record for a **poison item** — one that killed its worker
    ``crash_count`` times through the pool supervisor's bounded respawns.
    The record rides the normal lineage quarantine channel, so the coverage
    audit reads the item as *quarantined* (accounted for), never as a silent
    drop — and the pipeline moves on instead of crash-looping
    (``docs/robustness.md``)."""
    import types
    info = tracker.pieces.get(int(piece_index)) if piece_index is not None \
        else None
    path, row_group, num_rows = info if info else ('<unknown>', -1, -1)
    partition = tuple(partition or (0, 1))
    k, n = int(partition[0]), max(1, int(partition[1]))
    rows = num_rows if num_rows and num_rows > 0 else 1
    if n > 1 and num_rows and num_rows > 0:
        # the np.array_split contract the drop-partition slicing follows:
        # the first (num_rows % n) partitions carry one extra row
        rows = num_rows // n + (1 if k < num_rows % n else 0)
    piece = types.SimpleNamespace(path=path, row_group=row_group)
    return make_quarantine_record(
        piece, int(piece_index if piece_index is not None else -1),
        int(epoch or 0), partition, tracker.shard, 'worker-crash',
        RuntimeError('item killed {} worker(s); quarantined instead of '
                     'crash-looping'.format(crash_count)), rows=rows)


# -- replay -------------------------------------------------------------------

class _ReplayCollector:
    """Publish sink of the replay worker."""

    def __init__(self):
        self.items = []

    def __call__(self, payload):
        self.items.append(payload)


def _payload_to_columns(payload, schema) -> Dict[str, np.ndarray]:
    """Normalize any worker payload (row-dict list, column dict, arrow
    table) into a dict of numpy column arrays in payload-row order."""
    import pyarrow as pa
    if isinstance(payload, pa.Table):
        from petastorm_tpu.readers.batch_worker import BatchResultsReader
        out = {}
        for name in payload.column_names:
            field = schema.fields.get(name) if schema is not None else None
            column = payload.column(name)
            if field is not None:
                out[name] = BatchResultsReader._column_to_numpy(column, field)
            else:
                out[name] = column.to_numpy(zero_copy_only=False)
        return out
    if isinstance(payload, dict):
        return {k: np.asarray(v) if not isinstance(v, np.ndarray) else v
                for k, v in payload.items()}
    if isinstance(payload, list):   # row dicts
        from petastorm_tpu.jax_utils import JaxDataLoader
        return JaxDataLoader._collate(payload) if payload else {}
    raise TypeError('cannot replay payload of type {}'.format(type(payload)))


def replay_records(reader, records: List[Provenance],
                   offsets_per_record: Optional[List[np.ndarray]] = None
                   ) -> Dict[str, np.ndarray]:
    """Re-fetch the exact rows of ``records`` through the reader's own
    worker machinery (same predicate/partition/decode path) and return them
    as a dict of numpy columns, concatenated in record order.

    ``offsets_per_record`` optionally selects payload-row offsets per record
    (what :func:`replay` uses to reassemble a batch bit-exactly)."""
    worker_class = getattr(reader, '_worker_class', None)
    worker_args = getattr(reader, '_worker_args', None)
    replay_items = getattr(reader, '_replay_items', None)
    if worker_class is None or worker_args is None:
        raise RuntimeError('reader does not expose replay machinery')
    args = dict(worker_args)
    args.update(trace=False, health=False, lineage=False, latency=False,
                io_readahead=0, hedge=False)
    collector = _ReplayCollector()
    worker = worker_class(-1, collector, args)
    pieces_out = []
    try:
        for i, record in enumerate(records):
            if record is None:
                raise ValueError('cannot replay an evicted provenance record '
                                 '(raise the tracker record capacity)')
            if record.selection[0] == 'windows':
                raise NotImplementedError(
                    'replay of NGram window provenance is not supported')
            key = (record.piece_index, tuple(record.partition))
            item = (replay_items or {}).get(key, {})
            collector.items = []
            worker.process(record.piece_index,
                           worker_predicate=item.get('worker_predicate'),
                           shuffle_row_drop_partition=tuple(record.partition),
                           epoch=record.epoch)
            if len(collector.items) != 1:
                raise RuntimeError(
                    'replay of {}:{} published {} payloads (expected 1)'
                    .format(record.path, record.row_group,
                            len(collector.items)))
            columns = _payload_to_columns(collector.items[0],
                                          getattr(reader, 'schema', None))
            if offsets_per_record is not None:
                offsets = np.asarray(offsets_per_record[i], dtype=np.int64)
                columns = {k: v[offsets] for k, v in columns.items()}
            pieces_out.append(columns)
    finally:
        worker.shutdown()
    if not pieces_out:
        return {}
    if len(pieces_out) == 1:
        return pieces_out[0]
    keys = pieces_out[0].keys()
    out = {}
    for k in keys:
        parts = [p[k] for p in pieces_out]
        if any(p.dtype == object for p in parts):
            # mixed dense/object parts (e.g. a nullable field whose nulls
            # all fell in one row group): insert row-wise, never broadcast
            col = np.empty(sum(len(p) for p in parts), dtype=object)
            pos = 0
            for p in parts:
                for j in range(len(p)):
                    col[pos + j] = p[j]
                pos += len(p)
            out[k] = col
        else:
            out[k] = np.concatenate(parts)
    return out


def replay(reader, provenance) -> Dict[str, np.ndarray]:
    """Bit-exact re-fetch of recorded provenance through the reader's own
    row-group machinery.

    ``provenance`` may be a :class:`Provenance` record (returns all of that
    item's rows), a registered ``seq`` int, a list of either, a
    :class:`BatchProvenance`, or a loader batch dict carrying one under
    ``'_provenance'`` — the latter two reassemble the exact batch rows in
    the exact batch order."""
    tracker = getattr(reader, 'lineage', None)
    if isinstance(provenance, dict):
        provenance = batch_provenance_of(provenance) or provenance
    if isinstance(provenance, BatchProvenance):
        seqs = provenance.seqs()
        offsets = provenance.offsets()
        order = np.arange(len(seqs))
        unique_seqs = np.unique(seqs)
        records, offset_lists, positions = [], [], []
        for seq in unique_seqs:
            mask = seqs == seq
            record = tracker.resolve(int(seq)) if tracker is not None else None
            records.append(record)
            offset_lists.append(offsets[mask])
            positions.append(order[mask])
        columns = replay_records(reader, records, offset_lists)
        # reassemble in batch order: rows were concatenated per unique seq
        perm = np.concatenate(positions) if positions else np.empty(0, np.int64)
        inverse = np.empty(len(perm), dtype=np.int64)
        inverse[perm] = np.arange(len(perm))
        return {k: v[inverse] for k, v in columns.items()}
    if isinstance(provenance, Provenance):
        return replay_records(reader, [provenance])
    if isinstance(provenance, (int, np.integer)):
        record = tracker.resolve(int(provenance)) if tracker is not None \
            else None
        return replay_records(reader, [record])
    if isinstance(provenance, (list, tuple)):
        records = [tracker.resolve(int(p)) if isinstance(p, (int, np.integer))
                   else p for p in provenance]
        return replay_records(reader, records)
    raise TypeError('cannot replay {!r}'.format(type(provenance)))
