"""Deterministic fault injection for the read path: a seeded, scenario-driven
filesystem wrapper plus the ``PETASTORM_TPU_CHAOS`` harness hook.

Chaos-testing a storage pipeline only proves something when the chaos is
**replayable**: a flake that cannot be re-run with the same fault sequence is
a bug report nobody can act on. Every injection decision here is a pure
function of ``(seed, path, operation, occurrence-index)`` — run the same
scenario with the same seed over the same access sequence and the exact same
reads fail, straggle, truncate or kill. This generalizes the ad-hoc slow-IO
shim ``benchmark/readahead.py`` grew for BENCH_r07 (which is now a
fixed-latency scenario of this module) into the full fault model:

========================  ====================================================
scenario                  injected faults
========================  ====================================================
``transient-errors``      ``read()`` raises ``OSError(EIO)`` at ``error_rate``
                          (then a ``cooldown_reads`` clean window per file —
                          one row-group read spans MANY ``read()`` calls, so
                          a bounded retry provably recovers, which is the
                          property under test)
``tail-latency``          every read pays ``base_latency_s``; a ``tail_rate``
                          fraction pays ``tail_latency_s`` (heavy-tailed
                          first-byte latency — the hedging benchmark's store)
``read-hangs``            a ``hang_rate`` fraction of reads sleep ``hang_s``
                          (the straggler/wedge shape hedges + watchdogs see)
``truncated-reads``       a ``truncate_rate`` fraction of reads return short
                          data (corrupts the Arrow stream mid-parse; the
                          retry layer re-reads through a fresh handle)
``worker-kill``           after ``kill_after_reads`` reads, raise
                          :class:`SimulatedWorkerCrash` (at most ``max_kills``
                          per process) — kills the worker thread/process from
                          *inside* the read path
``cache-enospc``          shared-cache segment publication raises
                          ``OSError(ENOSPC)`` at ``enospc_rate`` (the cache
                          degrades to direct decode; see ``docs/cache.md``)
``trace-replay``          every read pays a first-byte latency + size/bandwidth
                          delay drawn from a *recorded* object-store trace
                          (``trace=<file-or-builtin-name>``; see
                          ``benchmark/traces/`` and ``docs/object_store.md``) —
                          deterministic per (seed, path, range, occurrence), so
                          hedge thresholds and range planning are tuned against
                          a realistic S3-shaped tail without cloud credentials
``host-death``            after ``die_after_batches`` delivered batches the
                          chosen host (``die_host``; ``-1`` = seed-derived)
                          raises ``podelastic.SimulatedHostDeath`` — the
                          elasticity plane's survivors must absorb its leases
                          (at most ``max_deaths`` per process)
``host-join``             after ``join_after_batches`` pod-wide delivered
                          batches a new host joins the pod and triggers a
                          bounded rebalance (at most ``max_joins`` per
                          process)
========================  ====================================================

Harness hook: set ``PETASTORM_TPU_CHAOS='<scenario>:<seed>'`` (e.g.
``transient-errors:1234``) and every :class:`ParquetPieceWorker` wraps its
filesystem in the scenario — including workers in **spawned process
interpreters**, which inherit the env var. Reader construction (metadata,
footers) stays clean: chaos arms exactly under the worker read path the
resilience layer protects. ``docs/robustness.md`` has the fault-model table
and the CI chaos-lane recipe.
"""

from __future__ import annotations

import errno
import hashlib
import logging
import os
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

#: Environment variable arming the chaos harness: ``'<scenario>:<seed>'``
#: (seed optional, default 0). Parsed once per process, cached.
CHAOS_ENV_VAR = 'PETASTORM_TPU_CHAOS'

#: Scenario registry: name -> default params. Every param can be overridden
#: by constructing a :class:`FaultInjector` directly (benchmarks do).
SCENARIOS: Dict[str, dict] = {
    'none': {},
    'transient-errors': dict(error_rate=0.25, cooldown_reads=64),
    'tail-latency': dict(base_latency_s=0.0, tail_rate=0.05,
                         tail_latency_s=0.25),
    'read-hangs': dict(hang_rate=0.03, hang_s=1.0, cooldown_reads=64),
    'truncated-reads': dict(truncate_rate=0.2, cooldown_reads=64),
    'worker-kill': dict(kill_after_reads=5, max_kills=1),
    'cache-enospc': dict(enospc_rate=1.0),
    # the BENCH_r07 slow-IO shim as a scenario: every read pays a fixed
    # latency (plus an optional per-byte bandwidth cost), faultlessly —
    # what benchmark/readahead.py's SlowFilesystem now resolves to
    'fixed-latency': dict(seconds_per_read=0.0, seconds_per_mb=0.0),
    # replay a recorded object-store latency/bandwidth distribution:
    # trace = path to a trace JSON or a builtin name under
    # benchmark/traces/ (e.g. 's3-us-east-1'); scales stretch/shrink the
    # recorded samples without re-recording
    'trace-replay': dict(trace='', latency_scale=1.0, bandwidth_scale=1.0),
    # pod-elasticity scenarios (consulted by petastorm_tpu.podelastic, NOT
    # the filesystem wrapper): kill one simulated host mid-epoch / admit a
    # late joiner. die_host=-1 derives the victim from the seed.
    'host-death': dict(die_host=-1, die_after_batches=3, max_deaths=1),
    'host-join': dict(join_after_batches=3, max_joins=1),
}


def trace_path(name: str) -> str:
    """Resolve a trace spec to a file path: an existing path is itself; a
    bare name resolves to the committed ``benchmark/traces/<name>.json``."""
    if os.path.exists(name):
        return name
    builtin = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           'benchmark', 'traces', name + '.json')
    if os.path.exists(builtin):
        return builtin
    raise ValueError('unknown trace {!r}: not a file, and no builtin trace '
                     '{}'.format(name, builtin))


def load_trace(name: str) -> dict:
    """Load + validate a recorded object-store trace (see
    ``docs/object_store.md`` for the format). Fails fast on a missing or
    malformed trace — a chaos run silently replaying nothing would be the
    worst failure mode."""
    import json
    with open(trace_path(name), 'r') as f:
        trace = json.load(f)
    for field in ('first_byte_latency_s', 'bandwidth_bytes_per_s'):
        samples = trace.get(field)
        if not isinstance(samples, list) or not samples \
                or not all(isinstance(s, (int, float)) and s > 0
                           for s in samples):
            raise ValueError('trace {!r}: {} must be a non-empty list of '
                             'positive numbers'.format(name, field))
    return trace


class SimulatedWorkerCrash(SystemExit):
    """An injected worker death. ``SystemExit`` by design: no ``except
    Exception`` handler on the worker path may swallow it — a thread worker
    dies exactly like one hit by an async kill, and a process worker's
    interpreter exits nonzero so the parent's liveness check fires."""


class FaultInjector:
    """Seeded, replayable fault decisions keyed by (path, op, occurrence).

    Thread-safe: the worker thread and its background readahead thread share
    one instance (they share the wrapped filesystem). Per-(path, op)
    occurrence counters make decisions deterministic for a given access
    sequence; ``max_consecutive`` caps back-to-back failures per path so a
    bounded retry provably recovers.
    """

    def __init__(self, scenario: str = 'none', seed: int = 0, **overrides):
        if scenario not in SCENARIOS:
            raise ValueError('unknown chaos scenario {!r}; valid: {}'.format(
                scenario, sorted(SCENARIOS)))
        params = dict(SCENARIOS[scenario])
        unknown = set(overrides) - set(params)
        if unknown:
            raise ValueError('unknown {} param(s) {}; valid: {}'.format(
                scenario, sorted(unknown), sorted(params)))
        params.update(overrides)
        self.scenario = scenario
        self.seed = int(seed)
        self.params = params
        self._lock = threading.Lock()
        self._occurrences: Dict[tuple, int] = {}
        self._cooldown: Dict[str, int] = {}
        self._kills = 0
        self._joins = 0
        self._reads = 0
        #: Injection tally by fault kind (diagnostics + test assertions).
        self.injected: Dict[str, int] = {}
        #: Injected *time* tally by kind, seconds (e.g. the total replayed
        #: trace latency) — the float companion of :attr:`injected`.
        self.injected_s: Dict[str, float] = {}
        self._trace: Optional[dict] = None
        if scenario == 'trace-replay':
            if not params['trace']:
                raise ValueError("trace-replay needs trace=<file-or-name>, "
                                 "e.g. 'trace-replay:0:trace=s3-us-east-1'")
            self._trace = load_trace(str(params['trace']))

    # -- decisions -------------------------------------------------------------

    def _occurrence(self, path: str, op: str) -> int:
        key = (path, op)
        with self._lock:
            n = self._occurrences.get(key, 0)
            self._occurrences[key] = n + 1
        return n

    def _uniform(self, path: str, op: str, occurrence: int) -> float:
        """Deterministic uniform [0, 1) draw for one decision point."""
        token = '{}:{}:{}:{}'.format(self.seed, os.path.basename(path), op,
                                     occurrence)
        digest = hashlib.md5(token.encode()).digest()
        return int.from_bytes(digest[:8], 'big') / float(1 << 64)

    def _count(self, kind: str) -> None:
        with self._lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1

    def _count_s(self, kind: str, seconds: float) -> None:
        with self._lock:
            self.injected_s[kind] = self.injected_s.get(kind, 0.0) + seconds

    def _in_cooldown(self, path: str) -> bool:
        """True (and consume one cooldown tick) while ``path`` is inside
        the clean window an injected fault opened. One row-group read spans
        many ``read()`` calls, so the window is sized in reads
        (``cooldown_reads``) — a bounded retry of the whole operation lands
        inside it and provably recovers."""
        if 'cooldown_reads' not in self.params:
            return False
        with self._lock:
            remaining = self._cooldown.get(path, 0)
            if remaining > 0:
                self._cooldown[path] = remaining - 1
                return True
        return False

    def _mark_fault(self, path: str) -> None:
        with self._lock:
            self._cooldown[path] = int(self.params['cooldown_reads'])

    # -- fs-side hooks ---------------------------------------------------------

    def before_read(self, path: str) -> None:
        """Runs before every wrapped ``read()``: may sleep (latency/hang),
        raise ``OSError`` (transient error) or :class:`SimulatedWorkerCrash`
        (worker kill)."""
        p = self.params
        occurrence = self._occurrence(path, 'read')
        with self._lock:
            self._reads += 1
            reads = self._reads
        if self.scenario == 'worker-kill':
            with self._lock:
                kill = (reads >= p['kill_after_reads']
                        and self._kills < p['max_kills'])
                if kill:
                    self._kills += 1
            if kill:
                self._count('worker_kill')
                raise SimulatedWorkerCrash(
                    'chaos: injected worker kill after {} reads '
                    '(seed {})'.format(reads, self.seed))
            return
        draw = self._uniform(path, 'read', occurrence)
        if self.scenario == 'transient-errors':
            if draw < p['error_rate'] and not self._in_cooldown(path):
                self._mark_fault(path)
                self._count('transient_error')
                raise OSError(errno.EIO,
                              'chaos: injected transient read error '
                              '(seed {}, occurrence {})'.format(
                                  self.seed, occurrence), path)
        elif self.scenario == 'tail-latency':
            delay = p['base_latency_s']
            if draw < p['tail_rate']:
                delay = p['tail_latency_s']
                self._count('tail_read')
            if delay > 0:
                time.sleep(delay)
        elif self.scenario == 'read-hangs':
            if draw < p['hang_rate'] and not self._in_cooldown(path):
                self._mark_fault(path)
                self._count('hang')
                time.sleep(p['hang_s'])

    def after_read(self, path: str, data):
        """Runs on every wrapped ``read()``'s returned bytes: may truncate
        (``truncated-reads``) or sleep (``fixed-latency`` — after the inner
        read completes, matching the BENCH_r07 shim's accounting)."""
        if self.scenario == 'fixed-latency':
            p = self.params
            nbytes = len(data) if data is not None else 0
            delay = (p['seconds_per_read']
                     + nbytes / (1024.0 * 1024.0) * p['seconds_per_mb'])
            if delay > 0:
                time.sleep(delay)
            return data
        if self.scenario != 'truncated-reads' or not data:
            return data
        occurrence = self._occurrence(path, 'truncate')
        if self._uniform(path, 'truncate', occurrence) \
                < self.params['truncate_rate'] \
                and not self._in_cooldown(path):
            self._mark_fault(path)
            self._count('truncated_read')
            return data[:max(1, len(data) // 2)]
        return data

    def trace_delay(self, path: str, offset: int, nbytes: int) -> None:
        """Replay one recorded object-store read against ``(path, offset,
        nbytes)``: sleep a first-byte latency sample plus ``nbytes`` over a
        bandwidth sample, both drawn deterministically from the trace.

        The draw is keyed on the *range* (path + offset + nbytes) plus a
        per-range occurrence counter: two different in-flight ranges replay
        independent samples regardless of thread completion order (the
        parallel range reader stays deterministic), while a hedge or retry
        of the SAME range re-draws — exactly the behavior that makes
        hedging win against a recorded tail."""
        if self._trace is None:
            return
        p = self.params
        key = '{}@{}+{}'.format(os.path.basename(path), offset, nbytes)
        occurrence = self._occurrence(key, 'trace')
        fb_samples = self._trace['first_byte_latency_s']
        bw_samples = self._trace['bandwidth_bytes_per_s']
        fb_draw = self._uniform(key, 'trace-fb', occurrence)
        bw_draw = self._uniform(key, 'trace-bw', occurrence)
        fb = fb_samples[min(int(fb_draw * len(fb_samples)),
                            len(fb_samples) - 1)]
        bw = bw_samples[min(int(bw_draw * len(bw_samples)),
                            len(bw_samples) - 1)]
        delay = fb * p['latency_scale']
        if nbytes:
            delay += nbytes / (bw * p['bandwidth_scale'])
        self._count('trace_reads')
        self._count_s('trace_latency_s', delay)
        if delay > 0:
            time.sleep(delay)

    # -- pod-elasticity hooks --------------------------------------------------

    def should_kill_host(self, host_index: int, batches_delivered: int) -> bool:
        """Consulted by ``podelastic.ElasticHost`` before each delivery step:
        True when this simulated host must die *now* (raise
        ``SimulatedHostDeath``). ``die_host`` picks the victim by index;
        ``die_host=-1`` derives it from the seed (deterministically, without
        needing to know the pod size: the draw selects a small index, and the
        first host at-or-above it to cross ``die_after_batches`` dies —
        replayable under the elasticity plane's round-robin stepping)."""
        if self.scenario != 'host-death':
            return False
        p = self.params
        if batches_delivered < p['die_after_batches']:
            return False
        die_host = int(p['die_host'])
        if die_host < 0:
            # seed-derived victim in [0, 4): pods smaller than the draw fall
            # through to the >= test below, so some host always dies
            die_host = int(self._uniform('pod', 'host-death', 0) * 4)
        with self._lock:
            if self._kills >= p['max_deaths']:
                return False
            if host_index != die_host and not (
                    int(p['die_host']) < 0 and host_index >= die_host):
                return False
            self._kills += 1
        self._count('host_death')
        return True

    def should_join_host(self, batches_delivered: int) -> bool:
        """Consulted by ``podelastic.ElasticPodSim`` between delivery steps:
        True when a new simulated host must join the pod *now* (at most
        ``max_joins`` per process, after ``join_after_batches`` pod-wide
        delivered batches)."""
        if self.scenario != 'host-join':
            return False
        p = self.params
        if batches_delivered < p['join_after_batches']:
            return False
        with self._lock:
            if self._joins >= p['max_joins']:
                return False
            self._joins += 1
        self._count('host_join')
        return True

    # -- cache-side hook -------------------------------------------------------

    def cache_put_fault(self, key: str) -> None:
        """Consulted by the shared cache before publishing a segment: raises
        ``OSError(ENOSPC)`` under the ``cache-enospc`` scenario (the cache's
        degrade path serves the decoded value anyway)."""
        if self.scenario != 'cache-enospc':
            return
        occurrence = self._occurrence(key, 'cache_put')
        if self._uniform(key, 'cache_put', occurrence) \
                < self.params['enospc_rate']:
            self._count('cache_enospc')
            raise OSError(errno.ENOSPC,
                          'chaos: injected ENOSPC on cache segment publish '
                          '(seed {})'.format(self.seed), key)


class FaultyFile:
    """File wrapper routing every ``read()`` through the injector (and
    counting reads/bytes on the owning filesystem, replacing the BENCH_r07
    shim's accounting)."""

    def __init__(self, inner, owner: 'FaultyFilesystem', path: str):
        self._inner = inner
        self._owner = owner
        self._path = path

    def read(self, *args, **kwargs):
        injector = self._owner.injector
        # the replayed trace keys on the byte range, so capture the offset
        # BEFORE the inner read advances it (only when a trace is armed —
        # tell() on every read would tax the faultless scenarios)
        offset = (self._inner.tell()
                  if injector.scenario == 'trace-replay' else 0)
        injector.before_read(self._path)
        data = self._inner.read(*args, **kwargs)
        nbytes = len(data) if data is not None else 0
        self._owner.on_read(nbytes)
        data = injector.after_read(self._path, data)
        injector.trace_delay(self._path, offset, nbytes)
        return data

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._inner.close()


class FaultyFilesystem:
    """fsspec-filesystem wrapper whose opened files consult a
    :class:`FaultInjector` on every ``read()``. Thread-safe (the worker
    thread and the readahead thread fault independently, exactly like two
    in-flight remote range requests)."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self.injector = injector
        self._lock = threading.Lock()
        self.read_calls = 0
        self.bytes_read = 0

    def on_read(self, nbytes: int) -> None:
        with self._lock:
            self.read_calls += 1
            self.bytes_read += nbytes

    def open(self, path, mode='rb', **kwargs):
        return FaultyFile(self._inner.open(path, mode, **kwargs), self, path)

    def __getattr__(self, name):
        return getattr(self._inner, name)


# -- the PETASTORM_TPU_CHAOS harness hook -------------------------------------

#: Scenarios injecting at the filesystem layer (everything except the
#: cache-publication fault, which arms inside the shared cache, and the
#: pod-elasticity scenarios, which arm inside podelastic's delivery loop).
_FS_SCENARIOS = frozenset({'transient-errors', 'tail-latency', 'read-hangs',
                           'truncated-reads', 'worker-kill',
                           'fixed-latency', 'trace-replay'})

_env_cache_lock = threading.Lock()
_env_cache: Dict[str, Optional[FaultInjector]] = {}


def parse_chaos(value: str) -> Optional[FaultInjector]:
    """``'<scenario>[:<seed>[:k=v,k=v]]'`` -> injector (``None`` for
    empty/'none'); e.g. ``'tail-latency:7:tail_rate=0.1,tail_latency_s=0.05'``.
    Raises on an unknown scenario or param name — a typo'd chaos spec
    silently running a CLEAN pass would be the worst possible failure mode
    for a chaos harness."""
    value = (value or '').strip()
    if not value or value == 'none':
        return None
    parts = value.split(':', 2)
    scenario = parts[0]
    seed = int(parts[1]) if len(parts) > 1 and parts[1] else 0
    overrides = {}
    if len(parts) > 2 and parts[2]:
        for pair in parts[2].split(','):
            key, _, raw = pair.partition('=')
            try:
                overrides[key.strip()] = int(raw)
            except ValueError:
                try:
                    overrides[key.strip()] = float(raw)
                except ValueError:
                    # string-valued params (trace-replay's trace=<name>)
                    overrides[key.strip()] = raw.strip()
    return FaultInjector(scenario, seed=seed, **overrides)


def reset_chaos_cache() -> None:
    """Drop the per-process injector cache so the NEXT armed run starts a
    fresh, replayable fault sequence (tests and benchmarks that run several
    chaos passes in one process call this between passes; production
    processes live one scenario for their lifetime)."""
    with _env_cache_lock:
        _env_cache.clear()


def chaos_from_env() -> Optional[FaultInjector]:
    """The process-wide injector configured by :data:`CHAOS_ENV_VAR`
    (``None`` when unset). One injector per (process, env value): the worker
    thread, readahead thread and shared cache of one interpreter share a
    fault sequence, keeping a run replayable."""
    value = os.environ.get(CHAOS_ENV_VAR, '').strip()
    if not value or value == 'none':
        return None
    with _env_cache_lock:
        injector = _env_cache.get(value)
        if injector is None:
            injector = parse_chaos(value)
            _env_cache[value] = injector
    return injector


def maybe_wrap(filesystem):
    """Wrap ``filesystem`` in the env-configured chaos scenario when one is
    armed and injects at the fs layer; pass through otherwise. Called by
    ``ParquetPieceWorker`` so chaos covers exactly the worker read path
    (spawned worker interpreters inherit the env var and wrap themselves)."""
    injector = chaos_from_env()
    if injector is None or injector.scenario not in _FS_SCENARIOS:
        return filesystem
    logger.warning('chaos armed: wrapping filesystem in scenario %r '
                   '(seed %d)', injector.scenario, injector.seed)
    return FaultyFilesystem(filesystem, injector)


def maybe_inject_cache_fault(key: str) -> None:
    """Shared-cache publication hook: raises ``OSError(ENOSPC)`` when the
    ``cache-enospc`` scenario is armed (no-op otherwise)."""
    injector = chaos_from_env()
    if injector is not None:
        injector.cache_put_fault(key)
