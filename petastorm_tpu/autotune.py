"""Model-predictive pipeline autotuner: close the loop over the PR 7 model.

Every earlier observability layer is read-only: the sensors say what the
pipeline did (``ReaderStats``, heartbeats, the tail-latency plane), the
roofline model says what the host could do (``profiler.predict_throughput``),
and the advisor ranks the knob changes that would close the gap
(``profiler.advise``) — but every knob is still set once at construction and
frozen. This module actuates the model: a :class:`PipelineController` thread
runs a **sense → predict → actuate** loop against a live reader.

- **Sense.** Each tick (default 5s) reads a ``ReaderStats`` snapshot delta
  (rates over the tick window, not lifetime averages), the rolling-window
  p99s from the latency plane, ``bottleneck_signals``, and the cached
  calibration profile.
- **Predict.** Replays :func:`petastorm_tpu.profiler.predict_throughput`
  over the **neighbor set** of the current configuration — workers ±1,
  readahead depth ±1 — using the *measured* per-worker efficiency factor
  (:func:`petastorm_tpu.profiler.measured_worker_efficiency`) so the model
  can predict negative scaling (the BENCH_r13 GIL convoy). The best
  predicted move is taken only when its expected gain clears the hysteresis
  threshold, and never when the (crude, documented) latency model predicts
  it breaches the reader's ``p99_e2e_ms`` SLO target. Ventilation window
  follows worker/readahead moves as a **companion** actuation (the same
  sizing formula construction uses); the results-queue bound moves on
  **sensor** evidence (a tail-stall verdict) rather than the throughput
  model, which has no term for it.
- **Actuate.** Live actuators, each documented in ``docs/autotune.md``:
  ``ThreadPool.resize`` / ``ProcessPool.resize`` (clean retirement — the
  lineage auditor stays exactly-once), ``RowGroupReadahead.set_depth``
  (broadcast over the process pool's control channel),
  ``ConcurrentVentilator.set_max_in_flight`` and
  ``ThreadPool.set_results_queue_bound``.

Honesty machinery: every action lands in a bounded ring as a structured
record carrying the sensor evidence and the predicted delta; the tick after
a move grades it (measured vs predicted), :meth:`PipelineController.report`
aggregates the model's error, and **revert-on-regression** undoes any move
whose measured throughput drops past the revert threshold, quarantining
that (knob, direction) for a configurable number of ticks. Anti-flap:
per-knob cooldowns plus a single in-flight ungraded move at a time.

Multi-reader arbitration (minimal-viable): controllers on one host discover
peers through atomically-written records in a shared scratch directory and
split the host CPU budget proportionally to each reader's measured deficit,
so two concurrent autotuned readers cannot oscillate fighting for cores
(:class:`HostArbiter`).

Default-off. Enable per reader with ``autotune=True`` (or an options dict)
on any factory, job-wide with ``PETASTORM_TPU_AUTOTUNE=1``, or on the CLI
with ``--autotune``; ``PETASTORM_TPU_AUTOTUNE=0`` is the kill switch and
wins over everything — no controller thread, no scratch files. See
``docs/autotune.md``.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

from petastorm_tpu import profiler
from petastorm_tpu.health import bottleneck_signals

logger = logging.getLogger(__name__)

#: Environment variable: ``1``/``true``/``on`` enables the controller for
#: every reader in the job; ``0``/``false``/``off`` is the kill switch and
#: overrides even an explicit ``autotune=`` kwarg (no thread, no files).
AUTOTUNE_ENV_VAR = 'PETASTORM_TPU_AUTOTUNE'

#: Environment variable naming the arbitration scratch directory (default:
#: ``<tempdir>/petastorm_tpu_autotune``). Only created once a controller
#: actually starts.
AUTOTUNE_DIR_ENV_VAR = 'PETASTORM_TPU_AUTOTUNE_DIR'

#: The knobs the controller may move.
KNOBS = ('workers_count', 'io_readahead', 'vent_window',
         'results_queue_bound')

#: Recognized ``autotune=dict(...)`` option keys (typos fail the factory,
#: the ``slo=`` discipline).
AUTOTUNE_OPTION_KEYS = ('tick_interval_s', 'hysteresis_pct', 'cooldown_ticks',
                        'revert_pct', 'quarantine_ticks', 'max_workers',
                        'calibrate', 'scratch_dir', 'actions_ring',
                        'grade_ticks_max', 'resize_timeout_s')

_DEFAULT_OPTIONS = {
    'tick_interval_s': 5.0,     # sense→predict→actuate cadence
    'hysteresis_pct': 10.0,     # min predicted gain before a move is taken
    'cooldown_ticks': 2,        # per-knob rest after any move on it
    'revert_pct': 10.0,         # measured drop that triggers the revert
    'quarantine_ticks': 10,     # (knob, direction) lockout after a revert
    'max_workers': None,        # None = host cpu budget (arbitrated)
    'calibrate': 'auto',        # get_calibration mode for the model input
    'scratch_dir': None,        # None = AUTOTUNE_DIR_ENV_VAR / tempdir
    'actions_ring': 256,        # bounded action-record ring
    'grade_ticks_max': 3,       # give up grading a move after this many
                                # item-less ticks (no revert, no error)
    'resize_timeout_s': 15.0,   # bound on each pool-resize quiesce
}

#: Ventilation-window slack beyond ``workers * (1 + lookahead)`` — the same
#: constant the reader applies at construction (reader.py).
VENT_EXTRA = 2

#: Windowed ``data_stall_fraction`` (goodput plane) above which the sensor
#: path proposes deepening io readahead: the device spent most of the tick
#: window waiting on data, so widen the host side regardless of what the
#: throughput model predicts.
DATA_STALL_SENSOR_THRESHOLD = 0.5


def resolve_autotune(autotune) -> Optional[dict]:
    """Resolve the ``autotune=`` kwarg against :data:`AUTOTUNE_ENV_VAR` into
    a validated options dict, or ``None`` when no controller must exist.

    The kill switch (env ``0``/``false``/``off``) wins over an explicit
    kwarg: a job-wide "stop self-tuning NOW" must not require touching
    every call site."""
    env = os.environ.get(AUTOTUNE_ENV_VAR, '').strip().lower()
    if env in ('0', 'false', 'off'):
        return None
    # an EMPTY options dict means "on, all defaults" (the bool-or-options
    # contract); every other falsy value — False, None, 0, '' — means off
    # and defers to the env var (autotune=0 must never START a controller)
    explicitly_on = isinstance(autotune, dict) or bool(autotune)
    if not explicitly_on and env not in ('1', 'true', 'on'):
        return None
    options = dict(_DEFAULT_OPTIONS)
    if isinstance(autotune, dict):
        unknown = set(autotune) - set(AUTOTUNE_OPTION_KEYS)
        if unknown:
            raise ValueError('unknown autotune option(s) {}; valid keys: {}'
                             .format(sorted(unknown),
                                     ', '.join(AUTOTUNE_OPTION_KEYS)))
        options.update(autotune)
    if float(options['tick_interval_s']) <= 0:
        raise ValueError('tick_interval_s must be positive, got {!r}'
                         .format(options['tick_interval_s']))
    for key in ('hysteresis_pct', 'revert_pct'):
        if float(options[key]) < 0:
            raise ValueError('{} must be >= 0, got {!r}'.format(
                key, options[key]))
    for key in ('cooldown_ticks', 'quarantine_ticks', 'actions_ring',
                'grade_ticks_max'):
        if int(options[key]) < 1:
            raise ValueError('{} must be >= 1, got {!r}'.format(
                key, options[key]))
    if options['calibrate'] not in ('cached', 'auto', 'force'):
        raise ValueError("calibrate must be 'cached', 'auto' or 'force', "
                         'got {!r}'.format(options['calibrate']))
    return options


def scratch_dir(options: Optional[dict] = None) -> str:
    """The arbitration scratch directory (not created here)."""
    if options and options.get('scratch_dir'):
        return str(options['scratch_dir'])
    env = os.environ.get(AUTOTUNE_DIR_ENV_VAR, '').strip()
    if env:
        return env
    return os.path.join(tempfile.gettempdir(), 'petastorm_tpu_autotune')


class HostArbiter:
    """Minimal-viable multi-reader arbitration through a shared scratch dir.

    Each controller atomically publishes one record per tick —
    ``{id, pid, ts, deficit, workers}`` — and reads its peers' records back.
    A record is *fresh* while its ``ts`` is within three tick intervals
    (wall clock, deliberately: the records cross process boundaries, where
    ``perf_counter`` readings are incomparable). The host CPU budget is
    split proportionally to each fresh controller's measured **deficit**
    (how far below its best-predicted rate it runs), floored at one worker
    each — so a saturated reader cedes cores to a starving one instead of
    both oscillating at the shared ceiling.
    """

    def __init__(self, directory: str, cpu_count: int,
                 tick_interval_s: float, controller_id: Optional[str] = None):
        self._dir = directory
        self._cpu = max(1, int(cpu_count))
        self._tick = float(tick_interval_s)
        self.controller_id = controller_id or uuid.uuid4().hex[:12]
        self._path = os.path.join(
            self._dir, 'controller-{}.json'.format(self.controller_id))

    def publish(self, deficit: float, workers: int) -> None:
        """Atomically publish this controller's record (creates the scratch
        dir on first use — i.e. only once a controller actually runs)."""
        from petastorm_tpu.utils import atomic_write
        os.makedirs(self._dir, exist_ok=True)
        record = {
            'id': self.controller_id,
            'pid': os.getpid(),
            # deliberate wall clock: freshness is judged across processes,
            # where monotonic readings are incomparable
            'ts': time.time(),  # petalint: disable=monotonic-clock
            'deficit': round(max(0.0, min(1.0, float(deficit))), 4),
            'workers': int(workers),
        }
        atomic_write(self._path, lambda f: json.dump(record, f))

    def peers(self) -> List[dict]:
        """Fresh peer records (this controller's own record included once
        published)."""
        # deliberate wall clock: see publish()
        now = time.time()  # petalint: disable=monotonic-clock
        records = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return records
        for name in sorted(names):
            if not (name.startswith('controller-')
                    and name.endswith('.json')):
                continue
            try:
                with open(os.path.join(self._dir, name)) as f:
                    record = json.load(f)
            except (OSError, ValueError):
                continue
            if now - float(record.get('ts', 0)) <= 3.0 * self._tick:
                records.append(record)
        return records

    def worker_cap(self, own_deficit: float) -> int:
        """This controller's share of the host CPU budget."""
        peers = self.peers()
        others = [p for p in peers if p.get('id') != self.controller_id]
        if not others:
            return self._cpu
        deficits = {p['id']: max(0.0, float(p.get('deficit', 0.0)))
                    for p in others}
        deficits[self.controller_id] = max(0.0, float(own_deficit))
        total = sum(deficits.values())
        n = len(deficits)
        if total <= 0:
            share = self._cpu / n
        else:
            share = self._cpu * deficits[self.controller_id] / total
        return max(1, min(self._cpu, int(round(share))))

    def cleanup(self) -> None:
        """Remove this controller's record (stop path)."""
        try:
            os.remove(self._path)
        except OSError:
            pass


class ReaderActuators:
    """The live knobs of one reader pipeline, duck-typed over the pool and
    ventilator. Built by the ``Reader``; the controller only ever talks to
    this adapter (tests substitute a fake)."""

    def __init__(self, pool, ventilator=None, pool_type: str = 'thread',
                 resize_timeout_s: float = 15.0, initial_readahead: int = 0):
        self._pool = pool
        self._ventilator = ventilator
        self.pool_type = pool_type
        self._resize_timeout_s = resize_timeout_s
        self._readahead_depth = initial_readahead

    # every getter returns the current value; every setter returns the
    # value actually in effect afterwards (a failed actuation returns the
    # old value, which the controller records as a no-op)

    def get_workers(self) -> int:
        return self._pool.workers_count

    def set_workers(self, n: int) -> int:
        resize = getattr(self._pool, 'resize', None)
        if resize is None:
            return self.get_workers()
        return resize(n, timeout_s=self._resize_timeout_s)

    def get_readahead(self) -> int:
        return self._readahead_depth

    def set_readahead(self, depth: int) -> int:
        setter = getattr(self._pool, 'set_readahead_depth', None)
        if setter is None:
            return self._readahead_depth
        setter(depth)
        self._readahead_depth = depth
        return depth

    def get_vent_window(self) -> Optional[int]:
        vent = self._ventilator
        return getattr(vent, 'max_in_flight', None) if vent else None

    def set_vent_window(self, bound: int) -> Optional[int]:
        vent = self._ventilator
        setter = getattr(vent, 'set_max_in_flight', None) if vent else None
        if setter is None:
            return self.get_vent_window()
        setter(bound)
        return bound

    def get_queue_bound(self) -> Optional[int]:
        return getattr(self._pool, 'results_queue_bound', None)

    def set_queue_bound(self, bound: int) -> Optional[int]:
        setter = getattr(self._pool, 'set_results_queue_bound', None)
        if setter is None:
            return self.get_queue_bound()
        setter(bound)
        return bound

    def reap(self) -> None:
        """Join any retired workers (the off-hot-path join)."""
        reap = getattr(self._pool, 'reap_retired', None)
        if reap is not None:
            reap(timeout_s=1.0)


class PipelineController:
    """The sense→predict→actuate loop over one reader's live actuators.

    Fully injectable for tests: ``snapshot_fn`` supplies ``ReaderStats``
    snapshots, ``calibration_fn`` the (possibly cached) roofline
    calibration, ``latency`` the ``PipelineLatency`` (window p99s),
    ``clock`` the timebase. :meth:`tick` is the public single step the
    thread loops over.
    """

    def __init__(self, actuators, snapshot_fn: Callable[[], dict],
                 calibration_fn: Optional[Callable[[], Optional[dict]]] = None,
                 latency=None, slo_targets: Optional[dict] = None,
                 options: Optional[dict] = None,
                 arbiter: Optional[HostArbiter] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self._actuators = actuators
        self._snapshot_fn = snapshot_fn
        self._calibration_fn = calibration_fn
        self._latency = latency
        self._slo_targets = dict(slo_targets or {})
        self.options = dict(_DEFAULT_OPTIONS)
        self.options.update(options or {})
        self._arbiter = arbiter
        self._clock = clock
        self._lock = threading.Lock()
        self._actions = deque(maxlen=int(self.options['actions_ring']))
        self._ticks = 0
        self._actions_total = 0
        self._reverts_total = 0
        self._calibration = None
        self._calibration_missing_logged = False
        self._prev_snapshot: Optional[dict] = None
        self._prev_ts: Optional[float] = None
        self._last_rates: Dict[str, float] = {}
        self._last_data_stall: Optional[float] = None
        # anti-flap state: knob -> tick until which it rests; (knob, dir) ->
        # tick until which that direction is quarantined
        self._cooldowns: Dict[str, int] = {}
        self._quarantine: Dict[tuple, int] = {}
        # the single in-flight ungraded action (plus its grading budget)
        self._pending: Optional[dict] = None
        self._pending_grade_ticks = 0
        self._worker_cap = None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> 'PipelineController':
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='petastorm-tpu-autotune')
        self._thread.start()
        return self

    def _run(self) -> None:
        interval = float(self.options['tick_interval_s'])
        while not self._stop_event.wait(interval):
            try:
                self.tick()
            except Exception:
                # the controller observes and nudges; it must never be able
                # to kill the pipeline it tunes
                logger.exception('autotune tick failed')

    def stop(self, join: bool = True) -> None:
        """Signal the thread to stop; with ``join`` also wait for it and
        drop the arbitration record. Idempotent."""
        self._stop_event.set()
        if self._arbiter is not None:
            self._arbiter.cleanup()
        if not join:
            return
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None

    # -- sensing ---------------------------------------------------------------

    _DELTA_KEYS = ('items_out', 'worker_io_s', 'readahead_io_s',
                   'readahead_wait_s', 'worker_decode_s',
                   'worker_publish_wait_s', 'queue_wait_s', 'bytes_moved',
                   # goodput plane seconds (docs/goodput.md): windowed so the
                   # data_stall_fraction sensor reflects the CURRENT epoch,
                   # not an hours-old cumulative average
                   'goodput_total_s', 'goodput_stall_s', 'goodput_h2d_s',
                   'goodput_device_s')

    def _sense(self) -> dict:
        now = self._clock()
        snapshot = self._snapshot_fn() or {}
        prev = self._prev_snapshot or {}
        window = (now - self._prev_ts) if self._prev_ts is not None else None
        delta = {key: max(0.0, (snapshot.get(key) or 0)
                          - (prev.get(key) or 0))
                 for key in self._DELTA_KEYS}
        self._prev_snapshot = snapshot
        self._prev_ts = now
        items = delta['items_out']
        rate = (items / window) if window and window > 0 else 0.0
        # window p99s from the latency plane beat the cumulative snapshot
        # keys: an hours-old histogram can never move again
        p50 = p99 = e2e_p99 = None
        if self._latency is not None:
            p99s = self._latency.window_p99s()
            p99 = p99s.get('queue_wait')
            e2e_p99 = p99s.get('e2e_batch')
            p50 = self._latency.quantile('queue_wait', 0.5, window=True)
        delta['queue_wait_p50_s'] = (p50 if p50 is not None
                                     else snapshot.get('queue_wait_p50_s',
                                                       0.0))
        delta['queue_wait_p99_s'] = (p99 if p99 is not None
                                     else snapshot.get('queue_wait_p99_s',
                                                       0.0))
        signals = bottleneck_signals(delta)
        from petastorm_tpu.workers.stats import data_stall_fraction
        return {
            'window_s': window,
            'items_delta': items,
            'items_per_s': rate,
            'e2e_p99_s': e2e_p99,
            'signals': signals,
            'data_stall_fraction': data_stall_fraction(delta),
            'snapshot_delta': delta,
        }

    def _get_calibration(self) -> Optional[dict]:
        if self._calibration is not None:
            return self._calibration
        if self._calibration_fn is None:
            return None
        try:
            self._calibration = self._calibration_fn()
        except Exception:
            logger.exception('autotune calibration failed; model moves '
                             'disabled until it succeeds')
            self._calibration = None
        if self._calibration is None and not self._calibration_missing_logged:
            self._calibration_missing_logged = True
            logger.info('autotune: no roofline calibration available — '
                        'model-predicted moves disabled, sensor-driven '
                        'moves (queue bound on tail stalls) stay active')
        return self._calibration

    # -- prediction ------------------------------------------------------------

    def _predict(self, calibration: dict, workers: int, readahead: int,
                 worker_efficiency: float) -> Optional[float]:
        ceilings = dict(calibration.get('ceilings') or {})
        return profiler.predict_throughput(
            ceilings, workers=workers,
            cpu_count=calibration.get('cpu_count') or 1,
            io_overlap=readahead > 0,
            in_process=self._actuators.pool_type != 'process',
            worker_efficiency=worker_efficiency)

    def _rows_per_group(self) -> float:
        cal = self._calibration or {}
        return float(cal.get('rows_per_group') or 0.0)

    def _predicted_p99_breach(self, base_predicted, cand_predicted,
                              capacity_scale: float, sense: dict) -> bool:
        """The (crude, documented) latency constraint: scale the measured
        window p99 by the predicted throughput ratio and any buffering
        capacity growth; block the move when the result breaches the
        reader's ``p99_e2e_ms`` SLO target. No measurement → no constraint
        (the revert path is the backstop)."""
        target_ms = self._slo_targets.get('p99_e2e_ms')
        measured = sense.get('e2e_p99_s')
        if target_ms is None or measured is None:
            return False
        scale = float(capacity_scale)
        if base_predicted and cand_predicted:
            scale *= base_predicted / cand_predicted
        return measured * scale * 1000.0 > float(target_ms)

    def _candidates(self, sense: dict) -> List[dict]:
        calibration = self._get_calibration()
        if calibration is None:
            return []
        workers = self._actuators.get_workers()
        readahead = self._actuators.get_readahead()
        rows_per_group = self._rows_per_group()
        measured_rows = sense['items_per_s'] * rows_per_group
        decode_ceiling = (calibration.get('ceilings') or {}).get('decode')
        efficiency = None
        if sense['signals']['bottleneck'] == 'decode':
            efficiency = profiler.measured_worker_efficiency(
                measured_rows, decode_ceiling, workers)
        efficiency = 1.0 if efficiency is None else efficiency
        base = self._predict(calibration, workers, readahead, efficiency)
        if not base:
            return []
        cap = self._worker_cap or (calibration.get('cpu_count') or 1)
        out = []

        def consider(knob, direction, value, predicted, capacity_scale=1.0):
            if predicted is None:
                return
            gain_pct = 100.0 * (predicted - base) / base
            if self._predicted_p99_breach(base, predicted, capacity_scale,
                                          sense):
                return
            out.append({'knob': knob, 'direction': direction, 'to': value,
                        'predicted_samples_per_s': predicted,
                        'predicted_gain_pct': gain_pct,
                        'worker_efficiency': efficiency,
                        'policy': 'model'})

        if workers + 1 <= cap:
            consider('workers_count', 'up', workers + 1,
                     self._predict(calibration, workers + 1, readahead,
                                   efficiency))
        if workers - 1 >= 1:
            consider('workers_count', 'down', workers - 1,
                     self._predict(calibration, workers - 1, readahead,
                                   efficiency))
        from petastorm_tpu.readers.readahead import (AUTO_INITIAL_DEPTH,
                                                     AUTO_MAX_DEPTH)
        # depth 1 cannot overlap anything: by the time the worker consumes
        # the head read no further read is scheduled, so the minimum USEFUL
        # depth is 2 (= AUTO_INITIAL_DEPTH) — 'up' from below jumps straight
        # there, and 'down' from there goes straight to off
        ra_up = (readahead + 1 if readahead >= AUTO_INITIAL_DEPTH
                 else AUTO_INITIAL_DEPTH)
        if readahead < ra_up <= AUTO_MAX_DEPTH:
            consider('io_readahead', 'up', ra_up,
                     self._predict(calibration, workers, ra_up, efficiency),
                     capacity_scale=(workers * (1 + ra_up) + VENT_EXTRA)
                     / max(1, workers * (1 + readahead) + VENT_EXTRA))
        if readahead > 0:
            ra_down = (readahead - 1 if readahead > AUTO_INITIAL_DEPTH
                       else 0)
            consider('io_readahead', 'down', ra_down,
                     self._predict(calibration, workers, ra_down,
                                   efficiency))
        return out

    def _sensor_candidates(self, sense: dict) -> List[dict]:
        """Moves the throughput model has no term for, driven directly by
        sensor evidence: a tail-stall verdict (queue-wait p99 dwarfing p50)
        asks for a deeper results queue to absorb the bursts, and a
        data-stalled consumer (the goodput plane's windowed
        ``data_stall_fraction`` — the device waited on data for most of
        the window) asks for deeper io readahead to widen the host side."""
        out = []
        signals = sense['signals']
        bound = self._actuators.get_queue_bound()
        if signals.get('tail_stall') and bound:
            new_bound = min(1024, max(bound + 1, bound * 3 // 2))
            if new_bound > bound:
                capacity_scale = new_bound / bound
                if not self._predicted_p99_breach(None, None, capacity_scale,
                                                  sense):
                    out.append({'knob': 'results_queue_bound',
                                'direction': 'up', 'to': new_bound,
                                'predicted_samples_per_s': None,
                                'predicted_gain_pct': None,
                                'policy': 'sensor',
                                'evidence': signals['bottleneck']})
        stall = sense.get('data_stall_fraction')
        if stall is not None and stall >= DATA_STALL_SENSOR_THRESHOLD:
            from petastorm_tpu.readers.readahead import (AUTO_INITIAL_DEPTH,
                                                         AUTO_MAX_DEPTH)
            readahead = self._actuators.get_readahead()
            ra_up = (readahead + 1 if readahead >= AUTO_INITIAL_DEPTH
                     else AUTO_INITIAL_DEPTH)
            if readahead < ra_up <= AUTO_MAX_DEPTH:
                out.append({'knob': 'io_readahead', 'direction': 'up',
                            'to': ra_up,
                            'predicted_samples_per_s': None,
                            'predicted_gain_pct': None,
                            'policy': 'sensor',
                            'evidence': 'data_stall_fraction={}'.format(
                                round(stall, 4))})
        return out

    # -- actuation -------------------------------------------------------------

    def _apply(self, candidate: dict) -> dict:
        knob = candidate['knob']
        to = candidate['to']
        before = after = None
        companion = None
        if knob == 'workers_count':
            before = self._actuators.get_workers()
            after = self._actuators.set_workers(to)
        elif knob == 'io_readahead':
            before = self._actuators.get_readahead()
            after = self._actuators.set_readahead(to)
        elif knob == 'vent_window':
            before = self._actuators.get_vent_window()
            after = self._actuators.set_vent_window(to)
        elif knob == 'results_queue_bound':
            before = self._actuators.get_queue_bound()
            after = self._actuators.set_queue_bound(to)
        if knob in ('workers_count', 'io_readahead') and after == to:
            # companion actuation: keep the ventilation window covering
            # every worker's prefetch horizon (the construction formula)
            workers = self._actuators.get_workers()
            lookahead = self._actuators.get_readahead()
            window = workers * (1 + lookahead) + VENT_EXTRA
            if self._actuators.set_vent_window(window) == window:
                companion = {'vent_window': window}
        return {'from': before, 'applied': after, 'companion': companion}

    def _record(self, action: dict) -> None:
        with self._lock:
            self._actions.append(action)
            self._actions_total += 1

    def _revert(self, action: dict, sense: dict) -> None:
        knob = action['knob']
        inverse = {'knob': knob, 'direction': 'revert', 'to': action['from']}
        applied = self._apply(inverse)
        quarantine_until = self._ticks + int(self.options['quarantine_ticks'])
        with self._lock:
            self._quarantine[(knob, action['direction'])] = quarantine_until
            self._reverts_total += 1
        self._record({
            'tick': self._ticks,
            'knob': knob,
            'direction': 'revert',
            'from': action['to'],
            'to': action['from'],
            'applied': applied['applied'],
            'policy': 'revert',
            'reverts_tick': action['tick'],
            'measured_samples_per_s': sense['items_per_s'],
            'evidence': {'measured_delta_pct':
                         action.get('measured_delta_pct')},
            'quarantined_until_tick': quarantine_until,
        })
        logger.warning(
            'autotune reverted %s %s->%s: measured throughput dropped '
            '%.1f%% after the move (predicted %+.1f%%); direction '
            'quarantined for %d ticks', knob, action['from'], action['to'],
            -(action.get('measured_delta_pct') or 0.0),
            action.get('predicted_gain_pct') or 0.0,
            int(self.options['quarantine_ticks']))
        # the undo actuation can stall the pipeline too: restart the sense
        # baseline so the next window measures post-revert flow only
        self._prev_snapshot = self._snapshot_fn() or {}
        self._prev_ts = self._clock()

    def _grade_pending(self, sense: dict) -> None:
        action = self._pending
        if action is None:
            return
        if sense['items_delta'] < 1:
            # nothing flowed this tick: a rate of zero says "idle consumer",
            # not "the move was bad" — extend the grading window
            self._pending_grade_ticks += 1
            if self._pending_grade_ticks >= int(
                    self.options['grade_ticks_max']):
                with self._lock:   # the dict is in the ring; readers copy it
                    action['graded'] = 'no-data'
                self._pending = None
            return
        pre = action.get('pre_samples_per_s') or 0.0
        post = sense['items_per_s']
        grade = {'measured_samples_per_s': round(post, 3)}
        measured_delta = None
        if pre > 0:
            measured_delta = 100.0 * (post - pre) / pre
            grade['measured_delta_pct'] = round(measured_delta, 1)
            predicted = action.get('predicted_gain_pct')
            if predicted is not None:
                grade['prediction_error_pct'] = round(
                    predicted - measured_delta, 1)
            grade['graded'] = 'measured'
        else:
            grade['graded'] = 'no-baseline'
        with self._lock:
            # the action dict already sits in the ring: mutate it under the
            # same lock actions()/report() copy it under, or a concurrent
            # /autotune scrape hits "dict changed size during iteration"
            action.update(grade)
        self._pending = None
        if measured_delta is not None \
                and measured_delta < -float(self.options['revert_pct']):
            self._revert(action, sense)

    # -- the loop --------------------------------------------------------------

    def tick(self) -> Optional[dict]:
        """One sense→predict→actuate step; returns the action taken (or
        ``None``). The background thread calls this every
        ``tick_interval_s``; tests call it directly."""
        self._ticks += 1
        self._actuators.reap()
        sense = self._sense()
        if sense['window_s'] is None:
            return None     # first tick: baseline only
        self._grade_pending(sense)
        self._last_rates = {'items_per_s': sense['items_per_s']}
        self._last_data_stall = sense.get('data_stall_fraction')
        # arbitration: publish our deficit, read back our CPU share
        calibration = self._get_calibration()
        cap = None
        if self._arbiter is not None:
            deficit = 0.0
            if calibration is not None:
                best = self._predict(
                    calibration,
                    int(self.options.get('max_workers')
                        or calibration.get('cpu_count') or 1),
                    1, 1.0)
                measured_rows = sense['items_per_s'] * self._rows_per_group()
                if best:
                    deficit = max(0.0, 1.0 - measured_rows / best)
            try:
                self._arbiter.publish(deficit, self._actuators.get_workers())
                cap = self._arbiter.worker_cap(deficit)
            except OSError:
                # an unwritable scratch dir (another user owns the shared
                # default under /tmp) must cost the arbitration layer, not
                # the whole controller — drop to solo operation, loudly
                logger.warning(
                    'autotune: arbitration scratch dir unusable; '
                    'continuing without multi-reader arbitration',
                    exc_info=True)
                self._arbiter = None
        max_workers = self.options.get('max_workers')
        if max_workers:
            cap = min(cap, int(max_workers)) if cap else int(max_workers)
        if cap is not None:
            self._worker_cap = cap
        if self._pending is not None:
            return None     # one ungraded move at a time (anti-flap)
        if sense['items_delta'] < 1:
            return None     # no flow: nothing to optimize, nothing to grade
        candidates = self._candidates(sense) + self._sensor_candidates(sense)
        hysteresis = float(self.options['hysteresis_pct'])
        viable = []
        for candidate in candidates:
            key = (candidate['knob'], candidate['direction'])
            if self._cooldowns.get(candidate['knob'], 0) > self._ticks:
                continue
            if self._quarantine.get(key, 0) > self._ticks:
                continue
            gain = candidate['predicted_gain_pct']
            if gain is not None and gain < hysteresis:
                continue
            viable.append(candidate)
        if not viable:
            return None
        # best predicted gain first; sensor moves (no prediction) rank last
        viable.sort(key=lambda c: -(c['predicted_gain_pct'] or -1e-9))
        chosen = viable[0]
        applied = self._apply(chosen)
        action = dict(chosen)
        action.update({
            'tick': self._ticks,
            'from': applied['from'],
            'applied': applied['applied'],
            'companion': applied['companion'],
            'pre_samples_per_s': round(sense['items_per_s'], 3),
            'evidence': {
                'bottleneck': sense['signals']['bottleneck'],
                'items_per_s': round(sense['items_per_s'], 3),
                'queue_wait_p99_s': round(
                    sense['snapshot_delta']['queue_wait_p99_s'] or 0.0, 6),
                'e2e_p99_s': sense['e2e_p99_s'],
                'worker_cap': self._worker_cap,
            },
        })
        if action['predicted_samples_per_s'] is not None:
            action['predicted_samples_per_s'] = round(
                action['predicted_samples_per_s'], 1)
        if action['predicted_gain_pct'] is not None:
            action['predicted_gain_pct'] = round(
                action['predicted_gain_pct'], 1)
        self._record(action)
        self._cooldowns[chosen['knob']] = (
            self._ticks + int(self.options['cooldown_ticks']))
        if applied['applied'] == chosen['to']:
            self._pending = action
            self._pending_grade_ticks = 0
            # actuation can stall the pipeline it is measuring (a process
            # shrink quiesces for seconds): restart the sense baseline so
            # the grading window covers only post-move flow, not the stall
            # the move itself caused
            self._prev_snapshot = self._snapshot_fn() or {}
            self._prev_ts = self._clock()
        else:
            with self._lock:   # the dict is in the ring; readers copy it
                action['graded'] = 'actuation-failed'
        logger.info('autotune: %s %s -> %s (%s, predicted %+s%%)',
                    chosen['knob'], applied['from'], applied['applied'],
                    chosen['policy'], chosen.get('predicted_gain_pct'))
        return action

    # -- observation surfaces --------------------------------------------------

    def actions(self) -> List[dict]:
        """The bounded action ring, oldest first (JSON-able copies)."""
        with self._lock:
            return [dict(a) for a in self._actions]

    def gauges(self) -> dict:
        """Flat numeric gauges merged into the reader's stats snapshot
        (``/metrics`` and the metrics emitter pick them up), plus the
        string-valued ``autotune_last_knob`` (label-exported, the
        ``binding_stage`` idiom)."""
        with self._lock:
            last = self._actions[-1] if self._actions else None
            out = {
                'autotune_ticks': self._ticks,
                'autotune_actions_total': self._actions_total,
                'autotune_reverts_total': self._reverts_total,
            }
        out['autotune_workers'] = self._actuators.get_workers()
        out['autotune_readahead_depth'] = self._actuators.get_readahead()
        if self._last_data_stall is not None:
            out['autotune_data_stall_fraction'] = self._last_data_stall
        if self._worker_cap is not None:
            out['autotune_worker_cap'] = self._worker_cap
        if last is not None:
            out['autotune_last_knob'] = '{}:{}'.format(last['knob'],
                                                       last['direction'])
            if last.get('predicted_gain_pct') is not None:
                out['autotune_last_predicted_delta_pct'] = \
                    last['predicted_gain_pct']
            if last.get('measured_delta_pct') is not None:
                out['autotune_last_measured_delta_pct'] = \
                    last['measured_delta_pct']
        return out

    def report(self) -> dict:
        """The controller grading its own predictions: every ringed action,
        the aggregate model error (mean absolute predicted-vs-measured
        delta), and the direction hit rate — measured-vs-predicted error is
        how we know the model is honest. What ``/autotune`` serves and
        flight records embed."""
        actions = self.actions()
        graded = [a for a in actions
                  if a.get('prediction_error_pct') is not None]
        direction_hits = sum(
            1 for a in graded
            if (a.get('measured_delta_pct') or 0.0) * (
                a.get('predicted_gain_pct') or 0.0) > 0)
        with self._lock:
            quarantined = [
                {'knob': knob, 'direction': direction,
                 'until_tick': until}
                for (knob, direction), until in sorted(
                    self._quarantine.items())
                if until > self._ticks]
        report = {
            'ticks': self._ticks,
            'actions_total': self._actions_total,
            'reverts_total': self._reverts_total,
            'actions': actions,
            'quarantined': quarantined,
            'config': {
                'workers_count': self._actuators.get_workers(),
                'io_readahead': self._actuators.get_readahead(),
                'vent_window': self._actuators.get_vent_window(),
                'results_queue_bound': self._actuators.get_queue_bound(),
                'worker_cap': self._worker_cap,
                'pool_type': self._actuators.pool_type,
            },
            'options': {k: v for k, v in self.options.items()
                        if v is not None},
            'prediction': {
                'graded': len(graded),
                'mean_abs_error_pct': round(
                    sum(abs(a['prediction_error_pct']) for a in graded)
                    / len(graded), 1) if graded else None,
                'direction_hits': direction_hits,
                'direction_accuracy': round(direction_hits / len(graded), 3)
                if graded else None,
            },
            'last_rates': dict(self._last_rates),
        }
        if self._arbiter is not None:
            report['arbitration'] = {
                'controller_id': self._arbiter.controller_id,
                'peers': self._arbiter.peers(),
                'worker_cap': self._worker_cap,
            }
        return report

    def flight_summary(self) -> dict:
        """The compact ``autotune`` section of a flight record: the recent
        action tail plus the grading aggregate (a stall that follows a
        controller move must be attributable to it)."""
        report = self.report()
        return {
            'ticks': report['ticks'],
            'actions_total': report['actions_total'],
            'reverts_total': report['reverts_total'],
            'recent_actions': report['actions'][-10:],
            'prediction': report['prediction'],
            'config': report['config'],
        }
