"""Exception types shared across the framework.

Reference parity: ``NoDataAvailableError`` is part of the reference's top-level API
(``petastorm/__init__.py:15-17``); metadata errors mirror
``petastorm/etl/dataset_metadata.py:46-49``.
"""


class PetastormTpuError(Exception):
    """Base class for all framework errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader has no row groups to read (e.g. all filtered out)."""


class PetastormMetadataError(PetastormTpuError):
    """Raised when dataset metadata is missing or malformed."""


class PetastormMetadataGenerationError(PetastormTpuError):
    """Raised when metadata generation failed validation after a dataset write."""
