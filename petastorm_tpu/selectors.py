"""Row-group selectors: choose row groups via the stored secondary indexes.

Reference parity: ``petastorm/selectors.py`` — ``RowGroupSelectorBase``
(:21-29), ``SingleIndexSelector`` (:32), ``IntersectIndexSelector`` (:54),
``UnionIndexSelector`` (:78).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, Iterable, List, Set


class RowGroupSelectorBase(ABC):
    """Maps stored indexes to a set of selected row-group ordinals."""

    @abstractmethod
    def get_index_names(self) -> List[str]:
        """Names of the indexes this selector needs."""

    @abstractmethod
    def select_row_groups(self, index_dict: Dict) -> Set[int]:
        """Compute the selected row-group ordinals from the loaded indexes."""


class SingleIndexSelector(RowGroupSelectorBase):
    """Row groups containing any of the given values in one index."""

    def __init__(self, index_name: str, values_list: Iterable):
        self._index_name = index_name
        self._values = list(values_list)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, index_dict):
        indexer = index_dict[self._index_name]
        selected: Set[int] = set()
        for value in self._values:
            selected |= indexer.get_row_group_indexes(value)
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    """AND-composition: row groups selected by every child selector."""

    def __init__(self, single_index_selectors: List[SingleIndexSelector]):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, index_dict):
        sets = [s.select_row_groups(index_dict) for s in self._selectors]
        return set.intersection(*sets) if sets else set()


class UnionIndexSelector(RowGroupSelectorBase):
    """OR-composition: row groups selected by any child selector."""

    def __init__(self, single_index_selectors: List[SingleIndexSelector]):
        self._selectors = list(single_index_selectors)

    def get_index_names(self):
        return sorted({n for s in self._selectors for n in s.get_index_names()})

    def select_row_groups(self, index_dict):
        selected: Set[int] = set()
        for s in self._selectors:
            selected |= s.select_row_groups(index_dict)
        return selected
