"""Worker-side row-group readahead: overlap storage I/O with decode.

PR-1 telemetry (``BENCH_r06.json``) showed the remaining infeed stall lives
inside the piece workers: each worker performs a blocking ``read_row_group``
and only then decodes, so storage latency and decode CPU serialize. This
module pipelines them — a single background reader thread per worker issues
the parquet reads for the next K ventilated pieces while the worker thread
decodes the current one (the tf.data-style prefetch discipline petastorm's
ancestors rely on).

Design constraints that shaped the shape of this class:

- **One background reader thread.** A ``pq.ParquetFile`` handle is not safe
  for concurrent reads, and the readahead therefore keeps its *own*
  file-handle cache (see ``ParquetPieceWorker``), fully disjoint from the
  worker thread's. Cross-file read parallelism comes from ``workers_count``;
  the readahead's job is only to hide the current worker's next read behind
  its current decode.
- **FIFO contract.** The pool's worker loop hints the worker with the exact
  upcoming item order, and the worker consumes reads in that same order.
  :meth:`sync` therefore treats the outstanding prefetches as a prefix of the
  hinted plan list and self-heals (cancels everything) on any mismatch —
  a desynced prefetch degrades to an inline read, never to wrong data.
- **Stats without cross-thread races.** ``WorkerBase.record_time`` is not
  thread-safe against the pool draining ``stage_times``, so the background
  thread accumulates into this object's own lock-protected dict and the
  *worker thread* transfers it out on every :meth:`take` call
  (:meth:`drain_stats_into`).

``depth='auto'`` sizes K from live measurements: the background thread knows
the average read time, and the gap between consecutive :meth:`take` calls is
the worker's decode+publish time for one piece — their ratio is the live
io:decode ratio that :func:`petastorm_tpu.workers.stats.recommend_io_readahead`
derives from a ``ReaderStats`` snapshot on the consumer side.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

#: Upper bound for ``depth='auto'`` (also the ventilation-queue sizing bound
#: the reader uses for 'auto'); deeper queues only smooth variance once the
#: single reader thread is saturated.
AUTO_MAX_DEPTH = 8

#: Starting depth for ``depth='auto'`` until enough samples arrive.
AUTO_INITIAL_DEPTH = 2


class _Prefetch:
    """One in-flight background read."""

    __slots__ = ('key', 'piece', 'columns', 'table', 'error', 'done',
                 'cancelled', 'read_s')

    def __init__(self, key, piece, columns):
        self.key = key
        self.piece = piece
        self.columns = columns
        self.table = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.cancelled = False
        self.read_s = 0.0


class RowGroupReadahead:
    """Bounded prefetch queue + one background reader thread.

    :param read_fn: ``read_fn(piece, columns) -> pa.Table``; runs **only** on
        the background thread (it must use its own file handles).
    :param depth: max outstanding prefetched reads, or ``'auto'``. ``0`` is
        **dormant**: the machinery exists (hints flow, :meth:`set_depth` can
        activate it live) but nothing is prefetched — the shape the autotune
        controller constructs when the reader starts with readahead off.
    :param trace: record a ``readahead_read`` span per background read
        (stamped with the background thread's track, drained into the worker
        alongside the stats).
    :param beat: optional ``beat(stage)`` callable publishing the background
        reader thread's liveness (the owning worker routes it to its own
        heartbeat records as a ``readahead-<id>`` entity; see
        :mod:`petastorm_tpu.health`). Called from the background thread —
        must be cross-thread safe (``WorkerBase.beat_entity`` is).
    :param controlled: the depth is **controller-owned**
        (:mod:`petastorm_tpu.autotune`): the local auto-retune never runs —
        two controllers adjusting one knob would oscillate — and only
        :meth:`set_depth` moves it.
    """

    def __init__(self, read_fn, depth, trace: bool = False, beat=None,
                 controlled: bool = False):
        if depth != 'auto' and (not isinstance(depth, int) or depth < 0):
            raise ValueError(
                "readahead depth must be a non-negative int or 'auto', got "
                '{!r}'.format(depth))
        self._read_fn = read_fn
        self._controlled = controlled
        self._auto = depth == 'auto' and not controlled
        self._depth = AUTO_INITIAL_DEPTH if depth == 'auto' else depth
        self._trace = trace
        self._beat = beat
        self._lock = threading.Lock()
        self._scheduled: deque = deque()      # FIFO of un-consumed _Prefetch
        self._requests: queue.Queue = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # accumulated telemetry, drained into the worker on its own thread
        self._stats_times = {'readahead_io_s': 0.0, 'readahead_wait_s': 0.0}
        self._stats_counts = {'readahead_hits': 0, 'readahead_misses': 0}
        self._trace_spans: list = []
        # auto-depth measurement state (all mutated under self._lock)
        self._read_s_sum = 0.0
        self._read_samples = 0
        self._gap_s_sum = 0.0
        self._gap_samples = 0
        self._last_serve_end: Optional[float] = None

    # -- sizing ----------------------------------------------------------------

    @property
    def depth(self) -> int:
        """Current target depth (fixed, live auto-tuned, or
        controller-set)."""
        with self._lock:
            return self._depth

    def set_depth(self, depth: int) -> None:
        """Pin the target depth live (the autotune controller's actuator).

        Pinning disables the local auto-retune for good — once a controller
        owns the knob, two tuners must never fight over it. ``0`` makes the
        readahead dormant (outstanding reads drain normally, new ones are
        not scheduled); a later positive depth re-activates it."""
        if not isinstance(depth, int) or depth < 0:
            raise ValueError('readahead depth must be a non-negative int, '
                             'got {!r}'.format(depth))
        with self._lock:
            self._auto = False
            self._depth = min(depth, AUTO_MAX_DEPTH)

    def _retune_locked(self) -> None:
        if not self._auto or self._read_samples < 2 or self._gap_samples < 2:
            return
        avg_read = self._read_s_sum / self._read_samples
        avg_gap = self._gap_s_sum / self._gap_samples
        ratio = avg_read / max(avg_gap, 1e-9)
        self._depth = int(min(AUTO_MAX_DEPTH, max(1, math.ceil(ratio))))

    # -- scheduling ------------------------------------------------------------

    def sync(self, plans: List[Tuple]) -> int:
        """Reconcile outstanding prefetches with the ordered upcoming ``plans``
        (``(key, piece, columns)`` tuples) and schedule new reads up to the
        current depth. Returns the number of outstanding prefetches.

        The outstanding FIFO must be a prefix of ``plans``; any mismatch
        (an item was processed without consuming its read, or the pool
        re-ordered work) cancels every outstanding read — prefetching is an
        optimization, and falling back to inline reads is always correct.
        """
        with self._lock:
            if self._stopped:
                return 0
            matches = len(self._scheduled) <= len(plans) and all(
                entry.key == plan[0]
                for entry, plan in zip(self._scheduled, plans))
            if not matches:
                self._cancel_all_locked()
            for key, piece, columns in plans[len(self._scheduled):]:
                if len(self._scheduled) >= self._depth:
                    break
                entry = _Prefetch(key, piece, columns)
                self._scheduled.append(entry)
                self._requests.put(entry)
            occupancy = len(self._scheduled)
            if occupancy and self._thread is None:
                self._thread = threading.Thread(
                    target=self._reader_loop, daemon=True,
                    name='petastorm-tpu-readahead')
                self._thread.start()
        return occupancy

    def take(self, key):
        """The table prefetched for ``key`` (blocking on its completion), or
        ``None`` when the read was not prefetched — the caller reads inline.

        Must be called from the worker thread, in the same order reads were
        hinted. Time blocked here is recorded as both ``readahead_wait_s``
        (the un-hidden I/O) and the stall the caller folds into
        ``worker_io_s``.
        """
        now = time.perf_counter()
        with self._lock:
            entry = None
            if self._scheduled and self._scheduled[0].key == key:
                entry = self._scheduled.popleft()
            if entry is None:
                if self._depth > 0:
                    # a dormant (depth-0) readahead never prefetches, so an
                    # inline read is its contract, not a miss to diagnose
                    self._stats_counts['readahead_misses'] += 1
                # inline read follows; its end time is unknown — skip the
                # next decode-gap sample rather than pollute it
                self._last_serve_end = None
                return None
            if self._last_serve_end is not None:
                self._gap_s_sum += now - self._last_serve_end
                self._gap_samples += 1
        wait_start = time.perf_counter()
        entry.done.wait()
        waited = time.perf_counter() - wait_start
        with self._lock:
            self._stats_counts['readahead_hits'] += 1
            self._stats_times['readahead_wait_s'] += waited
            self._last_serve_end = time.perf_counter()
            self._retune_locked()
        if entry.error is not None:
            raise entry.error
        return entry.table

    def drain_stats_into(self, worker) -> None:
        """Transfer accumulated telemetry into ``worker`` (a ``WorkerBase``).
        Called from the worker thread so ``stage_times`` is never mutated
        concurrently with the pool's drain. The blocked-wait portion also
        counts as ``worker_io_s`` — it is the storage stall the readahead
        failed to hide, and keeping it there preserves the decode-derivation
        contract of ``finalize_item_times``."""
        with self._lock:
            times = dict(self._stats_times)
            counts = dict(self._stats_counts)
            for stage in self._stats_times:
                self._stats_times[stage] = 0.0
            for name in self._stats_counts:
                self._stats_counts[name] = 0
            spans, self._trace_spans = self._trace_spans, []
            occupancy = len(self._scheduled)
        for stage, seconds in times.items():
            if seconds:
                worker.record_time(stage, seconds)
        if times['readahead_wait_s']:
            worker.record_time('worker_io_s', times['readahead_wait_s'])
        for name, n in counts.items():
            if n:
                worker.record_count(name, n)
        if spans and getattr(worker, 'tracing_enabled', False):
            # already stamped with the background thread's (pid, tid) track
            worker.trace_spans.extend(spans)
        worker.record_gauge('readahead_depth', occupancy)

    # -- lifecycle -------------------------------------------------------------

    def _cancel_all_locked(self) -> None:
        for entry in self._scheduled:
            entry.cancelled = True
        self._scheduled.clear()
        self._last_serve_end = None

    def stop(self) -> None:
        """Cancel outstanding reads and stop the background thread."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._cancel_all_locked()
        self._requests.put(None)
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)

    # -- background thread -----------------------------------------------------

    def _reader_loop(self) -> None:
        beat = self._beat
        while True:
            if beat is not None:
                beat('idle')
            entry = self._requests.get()
            if entry is None:
                if beat is not None:
                    beat('stopped')
                return
            if entry.cancelled:
                entry.done.set()
                continue
            if beat is not None:
                beat('io')
            start = time.perf_counter()
            try:
                entry.table = self._read_fn(entry.piece, entry.columns)
            except BaseException as e:  # noqa: BLE001 - re-raised in take()
                entry.error = e
            entry.read_s = time.perf_counter() - start
            with self._lock:
                if not entry.cancelled:
                    self._stats_times['readahead_io_s'] += entry.read_s
                    if self._trace:
                        from petastorm_tpu.tracing import make_span
                        self._trace_spans.append(make_span(
                            'readahead_read', 'io', start, entry.read_s))
                self._read_s_sum += entry.read_s
                self._read_samples += 1
                self._retune_locked()
            entry.done.set()
