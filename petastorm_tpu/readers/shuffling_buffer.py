"""Shuffling buffers decorrelating the row-group read order from the yield order.

Capability parity with the reference's ``petastorm/reader_impl/shuffling_buffer.py``
(row-granular buffers) and ``petastorm/reader_impl/pytorch_shuffling_buffer.py``
(batched, column-major buffers) — but the batched variants here are numpy-native
so they can feed JAX/TPU pipelines (the host-side representation for a TPU input
pipeline is a numpy array; framework adapters convert at the edge).

Design notes:
- ``RandomShufflingBuffer`` uses the same O(1) random-pop-with-swap trick as the
  reference (``shuffling_buffer.py:94-180``): sample an index, swap the sampled
  item with the last, pop.
- ``BatchedRandomShufflingBuffer`` keeps whole columns as numpy arrays and
  samples a random permutation to slice batches from (reference algorithm doc at
  ``pytorch_shuffling_buffer.py:180-206``), which vectorizes shuffling instead
  of doing per-row python work.
"""

import collections

import numpy as np


class ShufflingBufferBase(object):
    """Row-granular buffer protocol (reference ``shuffling_buffer.py:22-58``)."""

    def add_many(self, items):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    def can_add(self):
        raise NotImplementedError

    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        raise NotImplementedError

    def finish(self):
        """Signal end of stream: buffer may drain below its decorrelation floor."""
        raise NotImplementedError


class NoopShufflingBuffer(ShufflingBufferBase):
    """Pass-through FIFO (reference ``shuffling_buffer.py:61-91``)."""

    def __init__(self):
        self._queue = collections.deque()
        self._done = False

    def add_many(self, items):
        self._queue.extend(items)

    def retrieve(self):
        return self._queue.popleft()

    def can_add(self):
        return not self._done

    def can_retrieve(self):
        return len(self._queue) > 0

    @property
    def size(self):
        return len(self._queue)

    def finish(self):
        self._done = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Bounded uniform-shuffling buffer (reference ``shuffling_buffer.py:94-180``).

    :param shuffling_buffer_capacity: soft capacity; ``can_add`` turns False at or
        above it (a single ``add_many`` may overshoot, as in the reference).
    :param min_after_retrieve: ``can_retrieve`` requires at least this many items
        buffered (decorrelation floor) until ``finish()`` is called.
    :param extra_capacity: headroom for the overshoot case.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve,
                 extra_capacity=1000, seed=None):
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._items = [None] * (shuffling_buffer_capacity + extra_capacity)
        self._size = 0
        self._done_adding = False
        self._random = np.random.RandomState(seed)

    def add_many(self, items):
        if self._done_adding:
            raise RuntimeError('Cannot add to a finished shuffling buffer')
        if not self.can_add():
            raise RuntimeError('Buffer is over capacity; check can_add() first')
        needed = self._size + len(items)
        if needed > len(self._items):
            self._items.extend([None] * (needed - len(self._items)))
        for item in items:
            self._items[self._size] = item
            self._size += 1

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Not enough items in the buffer; check can_retrieve()')
        idx = self._random.randint(self._size)
        item = self._items[idx]
        self._size -= 1
        self._items[idx] = self._items[self._size]
        self._items[self._size] = None
        return item

    def can_add(self):
        return self._size < self._capacity and not self._done_adding

    def can_retrieve(self):
        floor = 1 if self._done_adding else self._min_after_retrieve
        return self._size >= floor

    @property
    def size(self):
        return self._size

    def finish(self):
        self._done_adding = True


class BatchedBufferBase(object):
    """Column-major buffer protocol: add dicts of column arrays, retrieve
    fixed-size batches (reference ``pytorch_shuffling_buffer.py:23-83``)."""

    def __init__(self, batch_size):
        self._batch_size = batch_size
        self._done_adding = False
        self._size = 0

    def add_many(self, columns):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    def can_add(self):
        return not self._done_adding

    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        return self._size

    def finish(self):
        self._done_adding = True


class BatchedNoopShufflingBuffer(BatchedBufferBase):
    """Re-chunks incoming column batches into fixed-size batches, preserving
    order (reference ``pytorch_shuffling_buffer.py:111-159``)."""

    def __init__(self, batch_size):
        super(BatchedNoopShufflingBuffer, self).__init__(batch_size)
        self._chunks = collections.deque()   # deque of dict[str, ndarray]
        self._keys = None

    def add_many(self, columns):
        if self._done_adding:
            raise RuntimeError('Cannot add to a finished buffer')
        columns = {k: np.asarray(v) for k, v in columns.items()}
        if self._keys is None:
            self._keys = list(columns.keys())
        n = len(next(iter(columns.values())))
        if n:
            self._chunks.append(columns)
            self._size += n

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Not enough rows buffered; check can_retrieve()')
        want = min(self._batch_size, self._size)
        parts = collections.defaultdict(list)
        got = 0
        while got < want:
            chunk = self._chunks[0]
            avail = len(next(iter(chunk.values())))
            take = min(avail, want - got)
            if take == avail:
                self._chunks.popleft()
                for k, v in chunk.items():
                    parts[k].append(v)
            else:
                for k, v in chunk.items():
                    parts[k].append(v[:take])
                self._chunks[0] = {k: v[take:] for k, v in chunk.items()}
            got += take
        self._size -= got
        return {k: (v[0] if len(v) == 1 else np.concatenate(v)) for k, v in parts.items()}

    def can_retrieve(self):
        if self._done_adding:
            return self._size > 0
        return self._size >= self._batch_size


class BatchedRandomShufflingBuffer(BatchedBufferBase):
    """Vectorized shuffling buffer over column arrays.

    Keeps one pre-allocated numpy array per column; on ``retrieve`` draws a
    fresh random permutation head of ``batch_size`` indices, yields those rows
    and compacts by swapping the tail into the holes — the numpy translation of
    the reference's torch implementation (``pytorch_shuffling_buffer.py:162-304``).
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, batch_size,
                 seed=None):
        super(BatchedRandomShufflingBuffer, self).__init__(batch_size)
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._random = np.random.RandomState(seed)
        self._columns = None     # dict[str, ndarray] with capacity rows
        self._extra = collections.deque()  # overflow chunks not yet merged

    def can_add(self):
        return self._size < self._capacity and not self._done_adding

    def can_retrieve(self):
        floor = 1 if self._done_adding else max(self._min_after_retrieve, self._batch_size)
        return self._size >= floor

    def _ensure_storage(self, columns):
        if self._columns is None:
            self._columns = {}
            for k, v in columns.items():
                shape = (self._capacity,) + v.shape[1:]
                self._columns[k] = np.empty(shape, dtype=v.dtype)

    def add_many(self, columns):
        if self._done_adding:
            raise RuntimeError('Cannot add to a finished buffer')
        if not self.can_add():
            raise RuntimeError('Buffer is over capacity; check can_add() first')
        columns = {k: np.asarray(v) for k, v in columns.items()}
        n = len(next(iter(columns.values())))
        if n == 0:
            return
        self._ensure_storage(columns)
        fit = min(n, self._capacity - self._size)
        for k, v in columns.items():
            self._columns[k][self._size:self._size + fit] = v[:fit]
        if fit < n:
            # Overshoot tolerated as in the reference: spill to a side deque
            # merged back as space frees up.
            self._extra.append({k: v[fit:] for k, v in columns.items()})
        self._size += n

    def _merge_extra(self):
        stored = self._size - sum(len(next(iter(c.values()))) for c in self._extra)
        while self._extra and stored < self._capacity:
            chunk = self._extra[0]
            n = len(next(iter(chunk.values())))
            fit = min(n, self._capacity - stored)
            for k, v in chunk.items():
                self._columns[k][stored:stored + fit] = v[:fit]
            if fit < n:
                self._extra[0] = {k: v[fit:] for k, v in chunk.items()}
            else:
                self._extra.popleft()
            stored += fit

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Not enough rows buffered; check can_retrieve()')
        stored = self._size - sum(len(next(iter(c.values()))) for c in self._extra)
        want = min(self._batch_size, stored)
        perm = self._random.permutation(stored)
        take, rest = perm[:want], perm[want:]
        batch = {k: v[take].copy() for k, v in self._columns.items()}
        # Compact: move surviving rows to the front (vectorized gather).
        for k in self._columns:
            self._columns[k][:len(rest)] = self._columns[k][rest]
        self._size -= want
        self._merge_extra()
        return batch
