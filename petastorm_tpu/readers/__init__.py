"""Reader workers: row-granular (decode to python rows) and batch-granular
(arrow tables) — reference ``py_dict_reader_worker.py`` / ``arrow_reader_worker.py``."""
