"""Shared plumbing for workers that process one parquet row-group piece per
ventilated item (file-handle cache, stored-column selection, cache keying,
and the row-group readahead that overlaps storage I/O with decode)."""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import pyarrow.parquet as pq

from petastorm_tpu.cache import NullCache
from petastorm_tpu.workers.worker_base import WorkerBase

#: Bound on per-worker open parquet file handles. Many-file datasets used to
#: grow ``_open_files`` without limit — one handle (buffered reader + footer
#: metadata) per file ever touched, times workers. 32 keeps the common
#: few-files-per-shard case fully cached while bounding the many-file case.
FILE_HANDLE_CACHE_SIZE = 32

#: fsspec protocols that read from local memory/disk; everything else is
#: treated as remote storage where ``pre_buffer`` (coalesced column-chunk
#: reads) pays for itself.
_LOCAL_PROTOCOLS = frozenset({'file', 'local', 'memory'})


class FileHandleCache:
    """Small LRU of open :class:`pq.ParquetFile` handles, closing evictees.

    Each cache instance is owned by exactly one reading thread (the worker
    thread and the readahead thread hold disjoint instances, because a
    ``ParquetFile`` must not serve two concurrent reads); the lock only
    guards the bookkeeping so occupancy can be inspected cross-thread.
    """

    def __init__(self, open_fn, max_size: int = FILE_HANDLE_CACHE_SIZE):
        if max_size < 1:
            raise ValueError('max_size must be >= 1, got {}'.format(max_size))
        self._open_fn = open_fn
        self._max_size = max_size
        self._entries: 'OrderedDict[str, pq.ParquetFile]' = OrderedDict()
        self._lock = threading.Lock()

    def get(self, path: str) -> pq.ParquetFile:
        with self._lock:
            handle = self._entries.get(path)
            if handle is not None:
                self._entries.move_to_end(path)
                return handle
        handle = self._open_fn(path)
        evicted = []
        with self._lock:
            raced = self._entries.get(path)
            if raced is not None:
                self._entries.move_to_end(path)
                evicted.append(handle)   # lost a race; keep the cached one
                handle = raced
            else:
                self._entries[path] = handle
                while len(self._entries) > self._max_size:
                    evicted.append(self._entries.popitem(last=False)[1])
        for old in evicted:
            old.close()
        return handle

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, path: str) -> bool:
        with self._lock:
            return path in self._entries

    def close_all(self) -> None:
        with self._lock:
            entries, self._entries = self._entries, OrderedDict()
        for handle in entries.values():
            handle.close()


class ParquetPieceWorker(WorkerBase):
    """Base for row-group workers; subclasses implement :meth:`process`."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._filesystem = args['filesystem_factory']()
        self._dataset_path = args['dataset_path']
        self._schema = args['schema']                  # output view
        self._full_schema = args['full_schema']
        self._split_pieces = args['split_pieces']
        self._local_cache = args['local_cache']
        self._transform_spec = args['transform_spec']
        self._transformed_schema = args['transformed_schema']
        from petastorm_tpu.codecs import build_decode_overrides
        # built here (not in the factory) so only plain dicts cross the
        # process-pool pickle boundary
        self._decode_hints = args.get('decode_hints')
        self._decode_overrides = build_decode_overrides(
            self._full_schema, self._decode_hints)
        # pre_buffer coalesces a row group's column chunks into few large
        # ranged reads — the right shape for object stores (GCS/S3/HDFS),
        # pure overhead for local mmap-fast files
        protocol = getattr(self._filesystem, 'protocol', '')
        if isinstance(protocol, (tuple, list)):
            protocol = protocol[0] if protocol else ''
        self._pre_buffer = protocol not in _LOCAL_PROTOCOLS
        self._open_files = FileHandleCache(self._open_parquet)
        # cache-key components are per-worker constants: hash them once, not
        # per ventilated piece
        self._dataset_path_digest = hashlib.md5(
            str(self._dataset_path).encode()).hexdigest()
        self._decode_hints_digest = ''
        if self._decode_hints:
            self._decode_hints_digest = ':' + hashlib.md5(
                repr(sorted((k, sorted(v.items()))
                            for k, v in self._decode_hints.items())).encode()
            ).hexdigest()[:12]
        # -- readahead (see petastorm_tpu/readers/readahead.py) ----------------
        self._readahead = None
        self._prefetch_files: Optional[FileHandleCache] = None
        depth = args.get('io_readahead') or 0
        if depth:
            from petastorm_tpu.readers.readahead import RowGroupReadahead
            # the background thread gets its own handle cache: a ParquetFile
            # must never serve two concurrent reads
            self._prefetch_files = FileHandleCache(self._open_parquet)
            # the background reader thread publishes its own heartbeat
            # entity next to the worker's (a wedged prefetch read must be
            # attributable to the readahead thread, not the worker)
            readahead_entity = 'readahead-{}'.format(worker_id)
            self._readahead = RowGroupReadahead(
                self._readahead_read, depth, trace=self.tracing_enabled,
                beat=(lambda stage: self.beat_entity(readahead_entity, stage))
                if self.health_enabled else None)

    def shutdown(self):
        if self._readahead is not None:
            self._readahead.stop()
        if self._prefetch_files is not None:
            self._prefetch_files.close_all()
        self._open_files.close_all()

    def _open_parquet(self, path: str) -> pq.ParquetFile:
        handle = self._filesystem.open(path, 'rb')
        if self._pre_buffer:
            try:
                return pq.ParquetFile(handle, pre_buffer=True)
            except TypeError:  # pyarrow predating the kwarg
                pass
        return pq.ParquetFile(handle)

    def _parquet_file(self, path: str) -> pq.ParquetFile:
        return self._open_files.get(path)

    def _stored_columns(self, names: List[str], piece) -> List[str]:
        """Columns to physically read: requested minus partition-derived."""
        partition_keys = set(piece.partition_dict.keys())
        return [n for n in names if n not in partition_keys]

    # -- readahead -------------------------------------------------------------

    @property
    def prefetch_lookahead(self) -> int:
        """How many upcoming ventilated items the owning pool should hold back
        and pass to :meth:`prefetch_hint` (0 disables the pool's lookahead)."""
        return self._readahead.depth if self._readahead is not None else 0

    def prefetch_hint(self, upcoming_items) -> None:
        """Called by the pool's worker loop with the ordered ``(args, kwargs)``
        of the items this worker will process next; schedules background
        reads for the plannable ones."""
        if self._readahead is None:
            return
        plans = []
        for item_args, item_kwargs in upcoming_items:
            plan = self._plan_item(item_args, item_kwargs)
            if plan is not None:
                plans.append(plan)
        self._readahead.sync(plans)

    def _plan_item(self, item_args, item_kwargs) -> Optional[Tuple]:
        """``(key, piece, columns)`` of the primary read a future
        ``process(*item_args, **item_kwargs)`` call will issue, or ``None``
        when the item is not prefetchable (predicate items read in multiple
        dependent phases; cached items may skip the read entirely)."""
        params = dict(zip(('piece_index', 'worker_predicate',
                           'shuffle_row_drop_partition'), item_args))
        params.update(item_kwargs)
        if params.get('worker_predicate') is not None:
            return None
        if not isinstance(self._local_cache, NullCache):
            return None
        piece_index = params.get('piece_index')
        if piece_index is None:
            return None
        piece = self._split_pieces[piece_index]
        columns = self._planned_columns(piece)
        if columns is None:
            return None
        return self._read_key(piece, columns), piece, columns

    def _planned_columns(self, piece) -> Optional[List[str]]:
        """The exact column list the subclass's no-predicate load will pass to
        :meth:`_read_row_group` for ``piece`` (``None`` = not plannable).
        Overridden per worker type."""
        return None

    @staticmethod
    def _read_key(piece, columns: List[str]) -> Tuple:
        return (piece.path, piece.row_group, tuple(columns))

    def _readahead_read(self, piece, columns: List[str]):
        """The background thread's read path — its own file handles, no shared
        state with the worker thread."""
        return self._prefetch_files.get(piece.path).read_row_group(
            piece.row_group, columns=columns)

    # -- reads -----------------------------------------------------------------

    def _read_row_group(self, piece, columns: List[str]):
        """Timed parquet read — the one physical-read call all piece workers
        share, so ``worker_io_s`` covers every byte read from storage. With
        readahead enabled, prefetched reads are consumed here (only the
        blocked wait, if any, lands in ``worker_io_s``); unplanned reads fall
        back inline."""
        # entry beat: a read that never returns must be attributed to ``io``
        # (the completion beat inside record_time can only fire afterwards)
        self.beat('io')
        if self._readahead is not None:
            table = self._readahead.take(self._read_key(piece, columns))
            self._readahead.drain_stats_into(self)
            if table is not None:
                return table
        start = time.perf_counter()
        table = self._parquet_file(piece.path).read_row_group(
            piece.row_group, columns=columns)
        elapsed = time.perf_counter() - start
        self.record_time('worker_io_s', elapsed)
        self.record_span('parquet_read', 'io', start, elapsed,
                         args={'row_group': piece.row_group})
        return table

    def _decode_table(self, table, names) -> Dict:
        """Arrow table -> decoded numpy columns for ``names`` (full-schema
        typed, honoring per-field decode overrides) — the one columnar decode
        shared by the columnar worker and the row worker's window path."""
        from petastorm_tpu.readers.columnar_worker import _column_to_numpy
        self.beat('decode')   # entry beat: a wedged codec shows as `decode`
        start = time.perf_counter()
        out = {}
        for name in names:
            if name not in table.column_names:
                continue
            field = self._full_schema.fields[name]
            out[name] = _column_to_numpy(table.column(name), field,
                                         self._decode_overrides.get(name))
        self.record_span('decode_columns', 'decode', start,
                         time.perf_counter() - start)
        return out

    def _cache_key(self, prefix: str, piece) -> str:
        # decode_hints change what a decoded row group contains (e.g. image
        # resolution) — they must partition the cache, or a reader with
        # different hints would be served wrong-resolution data
        return '{}:{}:{}:{}{}'.format(
            prefix, self._dataset_path_digest,
            piece.path, piece.row_group, self._decode_hints_digest)
