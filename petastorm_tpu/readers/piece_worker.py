"""Shared plumbing for workers that process one parquet row-group piece per
ventilated item (file-handle cache, stored-column selection, cache keying,
and the row-group readahead that overlaps storage I/O with decode)."""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow.parquet as pq

from petastorm_tpu.cache import NullCache
from petastorm_tpu.lineage import (NEVER_QUARANTINE, LineageEnvelope,
                                   Provenance, make_quarantine_record,
                                   validate_decode_error_policy)
from petastorm_tpu.workers.worker_base import WorkerBase

#: Bound on per-worker open parquet file handles. Many-file datasets used to
#: grow ``_open_files`` without limit — one handle (buffered reader + footer
#: metadata) per file ever touched, times workers. 32 keeps the common
#: few-files-per-shard case fully cached while bounding the many-file case.
FILE_HANDLE_CACHE_SIZE = 32

#: fsspec protocols that read from local memory/disk; everything else is
#: treated as remote storage where ``pre_buffer`` (coalesced column-chunk
#: reads) pays for itself.
_LOCAL_PROTOCOLS = frozenset({'file', 'local', 'memory'})

#: Cap on the per-record ``row_offsets`` detail of a quarantine record — a
#: wholesale-corrupt row group must not ship thousands of offsets per item.
_QUARANTINE_OFFSET_CAP = 64

#: Bound on explicit ``('index', ...)`` selection detail per provenance
#: record: a predicate matching half of a 500k-row group must not ship (and
#: ring-retain) one Python int per matching row. Above the cap the selection
#: degrades to ``('opaque', n)`` — predicate readers are item-exact audited
#: anyway (``row_filtered``).
_SELECTION_INDEX_CAP = 4096


class DecodeErrorSink:
    """Per-item collector of cell-level decode failures (tolerant decode
    path, ``on_decode_error != 'raise'``): ``errors`` holds
    ``(row_offset, field_name, exception)`` tuples; ``dense_fields`` names
    columns that fell from the dense fast path to the tolerant object path
    and must be re-densified after the failing rows are dropped."""

    __slots__ = ('errors', 'dense_fields')

    def __init__(self):
        self.errors: List[Tuple[int, str, BaseException]] = []
        self.dense_fields = set()


class FileHandleCache:
    """Small LRU of open :class:`pq.ParquetFile` handles, closing evictees.

    Each cache instance is owned by exactly one reading thread (the worker
    thread and the readahead thread hold disjoint instances, because a
    ``ParquetFile`` must not serve two concurrent reads); the lock only
    guards the bookkeeping so occupancy can be inspected cross-thread.

    Entries key on ``(filesystem identity, path)``, not path alone:
    ``fs_key`` (a callable returning the identity of the filesystem
    ``open_fn`` currently resolves to) partitions the cache so a
    chaos/trace-wrapped filesystem and the clean one can never share a
    cached handle — a handle opened through a fault wrapper replays faults,
    one opened clean does not, and serving either for the other silently
    changes what a run measures.
    """

    def __init__(self, open_fn, max_size: int = FILE_HANDLE_CACHE_SIZE,
                 fs_key: Optional[Callable[[], object]] = None):
        if max_size < 1:
            raise ValueError('max_size must be >= 1, got {}'.format(max_size))
        self._open_fn = open_fn
        self._fs_key = fs_key if fs_key is not None else lambda: None
        self._max_size = max_size
        self._entries: 'OrderedDict[tuple, pq.ParquetFile]' = OrderedDict()
        self._lock = threading.Lock()

    def get(self, path: str) -> pq.ParquetFile:
        key = (self._fs_key(), path)
        with self._lock:
            handle = self._entries.get(key)
            if handle is not None:
                self._entries.move_to_end(key)
                return handle
        handle = self._open_fn(path)
        evicted = []
        with self._lock:
            raced = self._entries.get(key)
            if raced is not None:
                self._entries.move_to_end(key)
                evicted.append(handle)   # lost a race; keep the cached one
                handle = raced
            else:
                self._entries[key] = handle
                while len(self._entries) > self._max_size:
                    evicted.append(self._entries.popitem(last=False)[1])
        for old in evicted:
            old.close()
        return handle

    def invalidate(self, path: str) -> None:
        """Close and drop every cached handle for ``path`` — across ALL
        filesystem identities (retry hygiene: a handle that just failed
        mid-read may be stuck mid-stream — the next attempt must reopen,
        not resume a poisoned position)."""
        with self._lock:
            stale = [k for k in self._entries if k[1] == path]
            handles = [self._entries.pop(k) for k in stale]
        for handle in handles:
            handle.close()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, path: str) -> bool:
        with self._lock:
            return any(k[1] == path for k in self._entries)

    def close_all(self) -> None:
        with self._lock:
            entries, self._entries = self._entries, OrderedDict()
        for handle in entries.values():
            handle.close()


class ParquetPieceWorker(WorkerBase):
    """Base for row-group workers; subclasses implement :meth:`process`."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        from petastorm_tpu import faultfs
        from petastorm_tpu.resilience import (ResilientIO, resolve_hedge,
                                              resolve_retry)
        # chaos harness (docs/robustness.md): when PETASTORM_TPU_CHAOS is
        # armed, the worker's filesystem — and ONLY the worker's; reader
        # construction stays clean — wraps in the scenario's fault injector.
        # Spawned process workers inherit the env var and wrap themselves.
        self._filesystem = faultfs.maybe_wrap(args['filesystem_factory']())
        # -- resilient IO (retry + hedge; see petastorm_tpu/resilience.py) -----
        # pod observability (docs/pod_observability.md): read-plane spans
        # ride the tracing plane, io_range/peer_fetch latency rides the
        # latency plane — each gated on its host plane AND the podobs switch
        from petastorm_tpu.podobs import podobs_enabled
        observe_pod = podobs_enabled()
        self._observe_spans = observe_pod and self.tracing_enabled
        self._observe_latency = observe_pod and self.latency is not None
        retry_options = resolve_retry(args.get('retry', True))
        hedge_options = resolve_hedge(args.get('hedge', False))
        self._resilience = (ResilientIO(retry_options, hedge_options,
                                        observe_spans=self._observe_spans)
                            if retry_options or hedge_options else None)
        self._dataset_path = args['dataset_path']
        self._schema = args['schema']                  # output view
        self._full_schema = args['full_schema']
        self._split_pieces = args['split_pieces']
        self._local_cache = args['local_cache']
        self._transform_spec = args['transform_spec']
        self._transformed_schema = args['transformed_schema']
        from petastorm_tpu.codecs import (batched_decode_enabled,
                                          build_decode_overrides)
        # built here (not in the factory) so only plain dicts cross the
        # process-pool pickle boundary
        self._decode_hints = args.get('decode_hints')
        self._decode_overrides = build_decode_overrides(
            self._full_schema, self._decode_hints)
        # row-group-vectorized codec decode (docs/decode.md); the env kill
        # switch is read once per worker, never per cell
        self._batched_decode = batched_decode_enabled()
        # bytes-through plans (docs/decode.md "Device-side decode"): planned
        # columns skip host decode and ship as raw (n, stride) uint8 grids.
        # The reader plans once; workers only execute the shipped plan.
        self._device_plans = args.get('device_decode_plans') or {}
        # -- remote read plane (docs/object_store.md) --------------------------
        # 'serial': plain sequential reads; 'prebuffer': pyarrow coalesces
        # column chunks internally; 'ranged': explicit footer-planned
        # parallel range fetches with per-RANGE retry/hedge. Auto picks
        # prebuffer for object stores (GCS/S3/HDFS) and serial for local
        # mmap-fast files — the pre-knob behavior.
        from petastorm_tpu.objectstore import (ParallelRangeReader,
                                               resolve_remote_read)
        protocol = getattr(self._filesystem, 'protocol', '')
        if isinstance(protocol, (tuple, list)):
            protocol = protocol[0] if protocol else ''
        mode = resolve_remote_read(args.get('remote_read'))
        if mode is None:
            mode = ('serial' if protocol in _LOCAL_PROTOCOLS
                    else 'prebuffer')
        self._remote_read = mode
        self._pre_buffer = mode == 'prebuffer'
        # one range reader per worker, shared with the readahead thread
        # (thread-safe: every read builds its own buffer and store handles)
        self._range_reader = (ParallelRangeReader(
            self._filesystem, resilience=self._resilience,
            observe_spans=self._observe_spans,
            observe_latency=self._observe_latency)
            if mode == 'ranged' else None)
        self._open_files = FileHandleCache(
            self._open_parquet, fs_key=lambda: id(self._filesystem))
        # cache-key components are per-worker constants: hash them once, not
        # per ventilated piece
        self._dataset_path_digest = hashlib.md5(
            str(self._dataset_path).encode()).hexdigest()
        # the column view partitions the cache: two readers over the same
        # store with different schema_fields must not serve each other
        # narrower/wider payloads (the shared host-wide cache makes such
        # cross-reader collisions routine, not hypothetical)
        self._view_digest = hashlib.md5(
            ','.join(sorted(self._schema.fields)).encode()).hexdigest()[:12]
        # -- lineage / quarantine (see petastorm_tpu/lineage.py) ---------------
        self._on_decode_error = validate_decode_error_policy(
            args.get('on_decode_error', 'raise') if isinstance(args, dict)
            else 'raise')
        self._shard = args.get('shard', -1) if isinstance(args, dict) else -1
        # file ordinals by first appearance across the reader's pieces: the
        # same deterministic table the consumer-side tracker derives
        self._file_indexes: Dict[str, int] = {}
        for piece in self._split_pieces:
            self._file_indexes.setdefault(piece.path, len(self._file_indexes))
        #: ``(piece, piece_index, epoch, partition)`` of the item being
        #: processed (workers are single-item-at-a-time by construction).
        self._item_ctx = None
        #: Source-row offsets of the last fresh load (``None`` = unknown:
        #: cache hit, or lineage+quarantine both off so nobody tracks).
        self._last_offsets: Optional[np.ndarray] = None
        self._decode_hints_digest = ''
        if self._decode_hints:
            self._decode_hints_digest = ':' + hashlib.md5(
                repr(sorted((k, sorted(v.items()))
                            for k, v in self._decode_hints.items())).encode()
            ).hexdigest()[:12]
        # a bytes-through reader caches RAW (n, stride) grids where a host
        # reader caches decoded arrays — the representations must never be
        # served across that boundary (see docs/cache.md key schema)
        self._device_plans_digest = ''
        if self._device_plans:
            self._device_plans_digest = ':dd' + hashlib.md5(
                ','.join(sorted(self._device_plans)).encode()).hexdigest()[:8]
        # -- readahead (see petastorm_tpu/readers/readahead.py) ----------------
        self._readahead = None
        self._prefetch_files: Optional[FileHandleCache] = None
        depth = args.get('io_readahead') or 0
        # controller-owned depth (docs/autotune.md): the machinery must
        # exist even at depth 0 so the autotune controller can raise the
        # knob live on a reader that started with readahead off
        controlled = bool(args.get('readahead_controlled'))
        if depth or controlled:
            from petastorm_tpu.readers.readahead import RowGroupReadahead
            # the background thread gets its own handle cache: a ParquetFile
            # must never serve two concurrent reads
            self._prefetch_files = FileHandleCache(
                self._open_parquet, fs_key=lambda: id(self._filesystem))
            # the background reader thread publishes its own heartbeat
            # entity next to the worker's (a wedged prefetch read must be
            # attributable to the readahead thread, not the worker)
            readahead_entity = 'readahead-{}'.format(worker_id)
            self._readahead = RowGroupReadahead(
                self._readahead_read, depth, trace=self.tracing_enabled,
                beat=(lambda stage: self.beat_entity(readahead_entity, stage))
                if self.health_enabled else None,
                controlled=controlled)

    def set_readahead_depth(self, depth: int) -> None:
        """Live-set the prefetch depth (the autotune controller's actuator);
        no-op for workers built without the readahead machinery."""
        if self._readahead is not None:
            self._readahead.set_depth(depth)

    def shutdown(self):
        if self._resilience is not None:
            self._resilience.drain()
        if self._readahead is not None:
            self._readahead.stop()
        if self._prefetch_files is not None:
            self._prefetch_files.close_all()
        self._open_files.close_all()
        close_cache = getattr(self._local_cache, 'close', None)
        if close_cache is not None:
            # shared cache: flush host-wide counters and release this
            # process's pins (idempotent — thread workers share one instance)
            close_cache()

    def _open_parquet(self, path: str) -> pq.ParquetFile:
        handle = self._filesystem.open(path, 'rb')
        if self._pre_buffer:
            try:
                return pq.ParquetFile(handle, pre_buffer=True)
            except TypeError:  # pyarrow predating the kwarg
                pass
        return pq.ParquetFile(handle)

    def _parquet_file(self, path: str) -> pq.ParquetFile:
        return self._open_files.get(path)

    def _stored_columns(self, names: List[str], piece) -> List[str]:
        """Columns to physically read: requested minus partition-derived."""
        partition_keys = set(piece.partition_dict.keys())
        return [n for n in names if n not in partition_keys]

    # -- readahead -------------------------------------------------------------

    @property
    def prefetch_lookahead(self) -> int:
        """How many upcoming ventilated items the owning pool should hold back
        and pass to :meth:`prefetch_hint` (0 disables the pool's lookahead)."""
        return self._readahead.depth if self._readahead is not None else 0

    def prefetch_hint(self, upcoming_items) -> None:
        """Called by the pool's worker loop with the ordered ``(args, kwargs)``
        of the items this worker will process next; schedules background
        reads for the plannable ones."""
        if self._readahead is None:
            return
        plans = []
        for item_args, item_kwargs in upcoming_items:
            plan = self._plan_item(item_args, item_kwargs)
            if plan is not None:
                plans.append(plan)
        self._readahead.sync(plans)

    def _plan_item(self, item_args, item_kwargs) -> Optional[Tuple]:
        """``(key, piece, columns)`` of the primary read a future
        ``process(*item_args, **item_kwargs)`` call will issue, or ``None``
        when the item is not prefetchable (predicate items read in multiple
        dependent phases; cached items may skip the read entirely)."""
        params = dict(zip(('piece_index', 'worker_predicate',
                           'shuffle_row_drop_partition'), item_args))
        params.update(item_kwargs)
        if params.get('worker_predicate') is not None:
            return None
        piece_index = params.get('piece_index')
        if piece_index is None:
            return None
        piece = self._split_pieces[piece_index]
        if not isinstance(self._local_cache, NullCache):
            # Tier-2 remote prefetch (docs/cache.md): with the SHARED cache,
            # only keys the host does not already hold are worth reading —
            # plan the background (pre_buffer-coalesced) read for misses and
            # skip hits entirely. Per-reader caches (local-disk) keep the old
            # behavior: a maybe-cached item is not plannable.
            contains = getattr(self._local_cache, 'contains', None)
            if contains is None:
                return None
            cache_key = self._planned_cache_key(piece, params)
            if cache_key is None or contains(cache_key):
                return None
        columns = self._planned_columns(piece)
        if columns is None:
            return None
        return self._read_key(piece, columns), piece, columns

    def _planned_columns(self, piece) -> Optional[List[str]]:
        """The exact column list the subclass's no-predicate load will pass to
        :meth:`_read_row_group` for ``piece`` (``None`` = not plannable).
        Overridden per worker type."""
        return None

    def _planned_cache_key(self, piece, params) -> Optional[str]:
        """The exact cache key the subclass's no-predicate load will consult
        for this ventilated item (``None`` = the load is not cached), so the
        readahead planner can peek the shared cache before scheduling a
        prefetch. Overridden per worker type."""
        return None

    @staticmethod
    def _read_key(piece, columns: List[str]) -> Tuple:
        return (piece.path, piece.row_group, tuple(columns))

    def _readahead_read(self, piece, columns: List[str]):
        """The background thread's read path — its own file handles, no shared
        state with the worker thread. Retried under the shared policy (a
        transient storage error must not surface as a failed prefetch the
        worker re-raises); hedging stays on the synchronous path only — the
        background read is already asynchronous to the worker. In ranged
        mode the shared range reader carries its own per-range retry/hedge,
        so it is used directly (it never shares handles between threads —
        every read opens its own)."""
        if self._range_reader is not None:
            return self._range_reader.read_row_group(
                piece.path, piece.row_group, columns=columns)

        def read():
            return self._prefetch_files.get(piece.path).read_row_group(
                piece.row_group, columns=columns)
        if self._resilience is None or self._resilience.retry is None:
            return read()

        def reopen(_exc, _attempt):
            self._prefetch_files.invalidate(piece.path)
        return self._resilience.retry.call(
            read, on_retry=reopen, on_event=self._resilience._count,
            description='readahead_read({}:{})'.format(piece.path,
                                                       piece.row_group))

    # -- reads -----------------------------------------------------------------

    def _read_row_group(self, piece, columns: List[str]):
        """Timed parquet read — the one physical-read call all piece workers
        share, so ``worker_io_s`` covers every byte read from storage. With
        readahead enabled, prefetched reads are consumed here (only the
        blocked wait, if any, lands in ``worker_io_s``); unplanned reads fall
        back inline."""
        # entry beat: a read that never returns must be attributed to ``io``
        # (the completion beat inside record_time can only fire afterwards)
        self.beat('io')
        if self._readahead is not None:
            table = self._readahead.take(self._read_key(piece, columns))
            self._readahead.drain_stats_into(self)
            if table is not None:
                self._drain_resilience_events()
                return table
        start = time.perf_counter()
        table = self._resilient_read(piece, columns)
        elapsed = time.perf_counter() - start
        self.record_time('worker_io_s', elapsed)
        self.record_span('parquet_read', 'io', start, elapsed,
                         args={'row_group': piece.row_group})
        self._drain_resilience_events()
        return table

    def _resilient_read(self, piece, columns: List[str]):
        """One physical row-group read under the configured hedge (inner)
        and retry (outer) layers (``docs/robustness.md``).

        With hedging ON, every attempt opens a **fresh** parquet handle on
        its own thread: a losing read keeps running until its blocking call
        returns, and a ``pq.ParquetFile`` must never serve two concurrent
        reads — so the abandoned loser may not share the worker's handle
        cache. The open-per-read cost is the documented price of hedging
        (it targets remote tail-latency stores, where open is cheap next to
        the tail). Retry-only readers keep the cached handle and invalidate
        it before each retry.

        In ``remote_read='ranged'`` mode the whole-row-group layers are
        bypassed: the range reader applies retry AND hedge **per range**
        inside ``fetch_range`` — a straggling range is hedged alone, which
        is the entire point of planning the read as explicit ranges."""
        if self._range_reader is not None:
            return self._range_reader.read_row_group(
                piece.path, piece.row_group, columns=columns)
        resilience = self._resilience
        if resilience is None or not resilience.enabled:
            return self._parquet_file(piece.path).read_row_group(
                piece.row_group, columns=columns)
        description = 'read_row_group({}:{})'.format(piece.path,
                                                     piece.row_group)
        if resilience.hedge is not None:
            def fresh_read():
                handle = self._open_parquet(piece.path)
                try:
                    return handle.read_row_group(piece.row_group,
                                                 columns=columns)
                finally:
                    handle.close()
            return resilience.read(fresh_read, description=description)

        def cached_read():
            return self._parquet_file(piece.path).read_row_group(
                piece.row_group, columns=columns)

        def reopen(_exc, _attempt):
            self._open_files.invalidate(piece.path)
        return resilience.read(cached_read, on_retry=reopen,
                               description=description)

    def _drain_resilience_events(self) -> None:
        """Transfer retry/hedge counters into the worker's stats (worker
        thread only — the hedge helper threads and the readahead thread
        accumulate into the resilience object's own lock-protected dict,
        exactly like the readahead stats drain)."""
        if self._range_reader is not None:
            for name, n in self._range_reader.take_events().items():
                if n:
                    self.record_count(name, n)
            for span in self._range_reader.take_spans():
                self.record_span(*span)
            deltas = self._range_reader.take_latency()
            if deltas and self.latency is not None:
                self.latency.absorb(deltas)
        if self._resilience is None:
            return
        for name, n in self._resilience.take_events().items():
            if n:
                self.record_count(name, n)
        for span in self._resilience.take_spans():
            self.record_span(*span)

    def _decode_table(self, table, names,
                      error_sink: Optional[DecodeErrorSink] = None) -> Dict:
        """Arrow table -> decoded numpy columns for ``names`` (full-schema
        typed, honoring per-field decode overrides) — the one columnar decode
        shared by the columnar worker and the row worker's window path.

        ``error_sink`` (tolerant decode, ``on_decode_error != 'raise'``)
        collects per-cell codec failures instead of letting them propagate;
        the caller drops the failing rows via
        :meth:`_apply_quarantine_drops`. The dense fast path is tried first
        and the tolerant re-decode only runs for a column that actually
        failed, so a clean row group pays nothing for the policy."""
        from petastorm_tpu.readers.columnar_worker import _column_to_numpy
        self.beat('decode')   # entry beat: a wedged codec shows as `decode`
        start = time.perf_counter()
        out = {}
        path_counts = {'batched': 0, 'percell': 0}
        raw_bytes = 0
        for name in names:
            if name not in table.column_names:
                continue
            field = self._full_schema.fields[name]
            column = table.column(name)
            plan = self._device_plans.get(name)
            if plan is not None:
                # bytes-through: ship the raw payload grid; the loader (or
                # the reader's host fallback) decodes. A chunk that drifted
                # from the pinned layout host-decodes and repacks so the
                # column's representation stays uniform — never an error.
                from petastorm_tpu.ops.decode import (raw_column_view,
                                                      repack_to_raw)
                raw = raw_column_view(column, plan)
                if raw is None:
                    decoded = _column_to_numpy(column, field, None,
                                               batched=self._batched_decode,
                                               path_counts=path_counts)
                    raw = repack_to_raw(plan, decoded)
                out[name] = raw
                raw_bytes += raw.nbytes
                continue
            on_cell_error = None
            if error_sink is not None and field.codec is not None:
                def on_cell_error(row, exc, _name=name):
                    error_sink.errors.append((row, _name, exc))
            errors_before = len(error_sink.errors) if error_sink else 0
            out[name] = _column_to_numpy(column, field,
                                         self._decode_overrides.get(name),
                                         on_cell_error=on_cell_error,
                                         batched=self._batched_decode,
                                         path_counts=path_counts)
            if (error_sink is not None
                    and len(error_sink.errors) > errors_before
                    and field.shape is not None
                    and all(s is not None for s in field.shape)
                    and column.null_count == 0):
                # the fast path would have produced a dense (n, *shape)
                # array; after the bad rows are dropped, restore that
                error_sink.dense_fields.add(name)
        if path_counts['batched']:
            self.record_count('rows_decoded_batched', path_counts['batched'])
        if path_counts['percell']:
            self.record_count('rows_decoded_percell', path_counts['percell'])
        if raw_bytes:
            self.record_count('bytes_shipped_raw', raw_bytes)
        elapsed = time.perf_counter() - start
        self.record_latency('decode', elapsed)
        self.record_span('decode_columns', 'decode', start, elapsed)
        return out

    # -- lineage / quarantine ----------------------------------------------------

    @property
    def _tolerant_decode(self) -> bool:
        """True when decode/transform failures quarantine/skip instead of
        killing the worker."""
        return self._on_decode_error != 'raise'

    @property
    def _tracks_offsets(self) -> bool:
        return self.lineage_enabled or self._tolerant_decode

    def _begin_item(self, piece, piece_index: int, epoch: int,
                    partition) -> None:
        self._item_ctx = (piece, int(piece_index), int(epoch),
                          tuple(partition or (0, 1)))
        self._last_offsets = None

    def _make_provenance(self, selection: tuple, rows: int) -> Provenance:
        piece, piece_index, epoch, partition = self._item_ctx
        return Provenance(
            dataset=self._dataset_path_digest[:12],
            file_index=self._file_indexes.get(piece.path, -1),
            path=piece.path, row_group=piece.row_group, rows=int(rows),
            selection=selection, epoch=epoch, shard=self._shard,
            piece_index=piece_index, partition=partition,
            worker_id=self.worker_id)

    def _publish_item(self, payload, selection: tuple, rows: int) -> None:
        """Publish one result, wrapped with its provenance when lineage is
        on (the pool decides how the envelope crosses its boundary)."""
        if self.lineage_enabled:
            self.publish_func(LineageEnvelope(
                payload, self._make_provenance(selection, rows)))
        else:
            self.publish_func(payload)

    def _finish_item_empty(self) -> None:
        """Record that the current item was processed successfully but has
        nothing to publish (empty drop-partition slice, no predicate match,
        empty row group): the provenance rides the accounting channel so the
        audit sees a zero-row delivery, not a drop."""
        if self.lineage_enabled:
            self.record_empty_publish(self._make_provenance(('index', ()), 0))

    @staticmethod
    def _range_offsets(n: int) -> tuple:
        """Offsets of a fresh full read, kept SYMBOLIC (``('range', 0, n)``)
        so the clean hot path never materializes per-row arrays; quarantine
        drops and predicates produce real index arrays instead."""
        return ('range', 0, int(n))

    @staticmethod
    def _slice_offsets(offsets, lo: int, hi: int):
        """Offsets after a ``[lo:hi)`` payload slice (drop partitions)."""
        if offsets is None:
            return None
        if isinstance(offsets, tuple):
            base = offsets[1]
            return ('range', base + int(lo), base + int(hi))
        return offsets[lo:hi]

    def _compact_selection(self, offsets, rows_n: int) -> tuple:
        """The most compact selection describing the delivered source rows
        (``docs/lineage.md`` has the vocabulary). ``offsets`` is a symbolic
        ``('range', lo, hi)``, an int ndarray, or ``None`` (opaque)."""
        piece = self._item_ctx[0] if self._item_ctx else None
        source_rows = getattr(piece, 'num_rows', -1)
        if offsets is None:
            return ('opaque', int(rows_n))
        if isinstance(offsets, tuple):
            lo, hi = int(offsets[1]), int(offsets[2])
            if lo == 0 and hi == source_rows:
                return ('all', hi)
            return ('slice', lo, hi)
        n = len(offsets)
        if n == 0:
            return ('index', ())
        contiguous = (n == 1
                      or (int(offsets[-1]) - int(offsets[0]) == n - 1
                          and bool(np.all(np.diff(offsets) == 1))))
        if contiguous:
            lo, hi = int(offsets[0]), int(offsets[-1]) + 1
            if lo == 0 and source_rows is not None and hi == source_rows:
                return ('all', n)
            return ('slice', lo, hi)
        if n > _SELECTION_INDEX_CAP:
            # a huge scattered match set must not ship one Python int per
            # row through the control frame and the consumer ring
            return ('opaque', int(rows_n))
        return ('index', tuple(int(o) for o in offsets))

    def _decode_error_sink(self) -> Optional[DecodeErrorSink]:
        return DecodeErrorSink() if self._tolerant_decode else None

    def _quarantine_event(self, stage: str, error: BaseException,
                          rows: int, field: Optional[str] = None,
                          row_offsets=None) -> None:
        """Count one quarantine/skip event; record it when the policy is
        ``'quarantine'`` (``'skip'`` drops silently but still counts)."""
        self.record_count('rows_quarantined', int(rows))
        self.record_count('items_quarantined', 1)
        if self._on_decode_error != 'quarantine':
            return
        piece, piece_index, epoch, partition = self._item_ctx
        self.record_quarantine(make_quarantine_record(
            piece, piece_index, epoch, partition, self._shard, stage, error,
            field=field, rows=rows,
            row_offsets=(list(row_offsets)[:_QUARANTINE_OFFSET_CAP]
                         if row_offsets is not None else None)))

    def _quarantine_item(self, stage: str, error: BaseException,
                         rows: Optional[int] = None) -> bool:
        """Quarantine/skip a whole failing item; returns False when the
        error must propagate (policy ``'raise'``, or an infrastructure
        exception that no policy may swallow)."""
        if not self._tolerant_decode or isinstance(error, NEVER_QUARANTINE):
            return False
        piece = self._item_ctx[0]
        if rows is None:
            rows = piece.num_rows if (piece.num_rows or 0) >= 0 else 1
        self._quarantine_event(stage, error, rows)
        return True

    def _apply_quarantine_drops(self, columns: Dict[str, np.ndarray],
                                sink: DecodeErrorSink,
                                num_rows: int) -> Tuple[Dict, np.ndarray]:
        """Drop the rows that failed cell-level decode from every column
        (re-densifying columns the tolerant path demoted to object arrays),
        record the quarantine events, and return ``(columns,
        kept_offsets)``."""
        bad_rows = sorted({row for row, _field, _exc in sink.errors})
        by_field: Dict[str, List] = {}
        for row, field, exc in sink.errors:
            by_field.setdefault(field, []).append((row, exc))
        for field, fails in by_field.items():
            self._quarantine_event('decode', fails[0][1], rows=len(fails),
                                   field=field,
                                   row_offsets=[r for r, _e in fails])
        keep = np.ones(num_rows, dtype=bool)
        keep[np.asarray(bad_rows, dtype=np.int64)] = False
        kept = np.flatnonzero(keep)
        out = {}
        for name, arr in columns.items():
            arr = arr[kept] if len(arr) == num_rows else arr
            if name in sink.dense_fields and arr.dtype == object and len(arr):
                arr = np.stack(list(arr))
            out[name] = arr
        return out, kept

    def _cached_load(self, cache_key: str, fill):
        """``self._local_cache.get`` plus telemetry: shared-cache hit/miss/
        eviction deltas land in ``ReaderStats`` (and from there in
        ``/metrics``, ``/diagnostics``, flight records). A blocked
        single-flight wait beats ``io`` so the watchdog attributes it."""
        cache = self._local_cache
        take_events = getattr(cache, 'take_events', None)
        if take_events is None:
            return cache.get(cache_key, fill)
        self.beat('io')   # a cross-process fill wait is storage-side stall
        value = cache.get(cache_key, fill)
        for name, n in take_events().items():
            if n:
                self.record_count(name, n)
        # pod-tier observability (docs/pod_observability.md): peer_fetch
        # spans ride the tracing plane, peer_fetch latency the latency plane
        take_spans = getattr(cache, 'take_spans', None)
        if take_spans is not None:
            for span in take_spans():
                self.record_span(*span)
        take_latency = getattr(cache, 'take_latency', None)
        if take_latency is not None and self.latency is not None:
            deltas = take_latency()
            if deltas:
                self.latency.absorb(deltas)
        self.record_gauge('shared_cache_bytes', cache.occupancy_bytes())
        return value

    def _cache_key(self, prefix: str, piece) -> str:
        # decode_hints change what a decoded row group contains (e.g. image
        # resolution) — they must partition the cache, as must the column
        # view (host-wide shared tiers serve MANY readers; see docs/cache.md
        # for the full key schema) — otherwise a reader with different hints
        # or fields would be served wrong payloads
        return '{}:{}:{}:{}:{}{}{}'.format(
            prefix, self._dataset_path_digest, self._view_digest,
            piece.path, piece.row_group, self._decode_hints_digest,
            self._device_plans_digest)
