"""Shared plumbing for workers that process one parquet row-group piece per
ventilated item (file-handle cache, stored-column selection, cache keying)."""

from __future__ import annotations

import hashlib
import time
from typing import Dict, List

import pyarrow.parquet as pq

from petastorm_tpu.workers.worker_base import WorkerBase


class ParquetPieceWorker(WorkerBase):
    """Base for row-group workers; subclasses implement :meth:`process`."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._filesystem = args['filesystem_factory']()
        self._dataset_path = args['dataset_path']
        self._schema = args['schema']                  # output view
        self._full_schema = args['full_schema']
        self._split_pieces = args['split_pieces']
        self._local_cache = args['local_cache']
        self._transform_spec = args['transform_spec']
        self._transformed_schema = args['transformed_schema']
        from petastorm_tpu.codecs import build_decode_overrides
        # built here (not in the factory) so only plain dicts cross the
        # process-pool pickle boundary
        self._decode_hints = args.get('decode_hints')
        self._decode_overrides = build_decode_overrides(
            self._full_schema, self._decode_hints)
        self._open_files: Dict[str, pq.ParquetFile] = {}

    def shutdown(self):
        for f in self._open_files.values():
            f.close()

    def _parquet_file(self, path: str) -> pq.ParquetFile:
        if path not in self._open_files:
            self._open_files[path] = pq.ParquetFile(self._filesystem.open(path, 'rb'))
        return self._open_files[path]

    def _stored_columns(self, names: List[str], piece) -> List[str]:
        """Columns to physically read: requested minus partition-derived."""
        partition_keys = set(piece.partition_dict.keys())
        return [n for n in names if n not in partition_keys]

    def _read_row_group(self, piece, columns: List[str]):
        """Timed parquet read — the one physical-read call all piece workers
        share, so ``worker_io_s`` covers every byte read from storage."""
        start = time.perf_counter()
        table = self._parquet_file(piece.path).read_row_group(
            piece.row_group, columns=columns)
        self.record_time('worker_io_s', time.perf_counter() - start)
        return table

    def _decode_table(self, table, names) -> Dict:
        """Arrow table -> decoded numpy columns for ``names`` (full-schema
        typed, honoring per-field decode overrides) — the one columnar decode
        shared by the columnar worker and the row worker's window path."""
        from petastorm_tpu.readers.columnar_worker import _column_to_numpy
        out = {}
        for name in names:
            if name not in table.column_names:
                continue
            field = self._full_schema.fields[name]
            out[name] = _column_to_numpy(table.column(name), field,
                                         self._decode_overrides.get(name))
        return out

    def _cache_key(self, prefix: str, piece) -> str:
        # decode_hints change what a decoded row group contains (e.g. image
        # resolution) — they must partition the cache, or a reader with
        # different hints would be served wrong-resolution data
        hints = ''
        if self._decode_hints:
            hints = ':' + hashlib.md5(
                repr(sorted((k, sorted(v.items()))
                            for k, v in self._decode_hints.items())).encode()
            ).hexdigest()[:12]
        return '{}:{}:{}:{}{}'.format(
            prefix, hashlib.md5(str(self._dataset_path).encode()).hexdigest(),
            piece.path, piece.row_group, hints)
