"""Row-granular reader worker: reads one row group, decodes rows with codecs,
applies predicates/transforms/ngram, publishes lists of row dicts.

Reference parity: ``petastorm/py_dict_reader_worker.py`` — worker (:99-274),
predicate pushdown inside the worker (:188-252), row-level cache keyed by
dataset path + piece (:155-163), ngram assembly (:165-166), shuffle_row_drop
partitioning incl. ngram continuation rows (:260-273), results-queue reader
(:63-96).
"""

from __future__ import annotations

import threading
import time
from typing import List

import numpy as np

from petastorm_tpu.lineage import NEVER_QUARANTINE, unwrap_envelope
from petastorm_tpu.ngram import NGramWindowChunk
from petastorm_tpu.readers.piece_worker import ParquetPieceWorker
from petastorm_tpu.unischema import decode_row
from petastorm_tpu.utils import cast_partition_value


def _cast_partition_value(field, value: str):
    return cast_partition_value(field.numpy_dtype if field is not None else None, value)


class RowGroupResultsReader:
    """Consumer-side: buffers published row lists and pops one row at a time as
    schema namedtuples (reference ``PyDictReaderWorkerResultsQueueReader``)."""

    def __init__(self, schema, ngram, lineage=None):
        self._schema = schema
        self._ngram = ngram
        self._buffer: List = []
        if ngram is not None:
            self._offsets, self._base_offset, self._fields_at = \
                ngram.timestep_layout(schema.fields)
        # Multiple consumer threads may drain one reader concurrently
        # (reference ``test_multithreaded_reads``): without the lock, two
        # threads can both see an empty buffer, both fetch a chunk, and one
        # assignment silently overwrites the other's unconsumed rows.
        self._lock = threading.Lock()
        #: The reader's :class:`~petastorm_tpu.lineage.LineageTracker`;
        #: provenance envelopes unwrap (and register) here, and
        #: ``last_seq``/``last_row_offset`` name the source of the most
        #: recently popped row (single-consumer contract: with concurrent
        #: consumer threads the attribution is per-thread approximate).
        self._lineage = lineage if getattr(lineage, 'enabled', False) else None
        self._buffer_seq = None
        self.last_seq = None
        self.last_row_offset = None

    @property
    def batched_output(self) -> bool:
        return False

    def _chunk_window_dict(self, chunk, i):
        """Slice window ``i`` out of a columnar chunk as the same
        ``{offset: {field: value}}`` layout the per-row worker path ships."""
        start = chunk.starts[i]
        cols = chunk.columns
        return {off: {name: cols[name][start + off - self._base_offset]
                      for name in self._fields_at[off] if name in cols}
                for off in self._offsets}

    def read_next(self, pool):
        with self._lock:
            while not self._buffer:
                # raises EmptyResultError at end of stream; propagates to Reader
                item, seq = unwrap_envelope(pool.get_results(), self._lineage)
                self._buffer_seq = seq
                if isinstance(item, NGramWindowChunk):
                    self._buffer = [self._chunk_window_dict(item, i)
                                    for i in range(len(item))]
                else:
                    self._buffer = list(item)
            item = self._buffer.pop()
            # pop() takes the payload's tail: after it, len(buffer) IS the
            # popped row's offset within the published payload
            self.last_seq = self._buffer_seq
            self.last_row_offset = len(self._buffer)
        if self._ngram:
            # workers ship windows as plain dicts (namedtuple classes of
            # schema views cannot cross the process-pool pickle boundary);
            # assemble the per-timestep namedtuples here on the consumer
            return self._ngram.make_namedtuples(item, self._schema)
        return self._schema.make_namedtuple(**item)

    def discard_buffered(self):
        """Drop windows buffered from a partially-consumed published item —
        ``Reader.drain()`` must leave nothing stale for the next pass."""
        with self._lock:
            self._buffer = []

    def read_next_chunk(self, pool):
        """One published item, raw — the JAX loader's chunked NGram path pulls
        whole :class:`NGramWindowChunk`s and collates them vectorized. Only
        valid on a reader whose workers publish chunks
        (``Reader.ngram_chunked``) and must not be mixed with per-window
        ``read_next`` calls on a buffered item."""
        chunk, seq = unwrap_envelope(pool.get_results(), self._lineage)
        if seq is not None:
            self.last_seq = seq
            self.last_row_offset = None
        return chunk


class RowGroupWorker(ParquetPieceWorker):
    """Processes ventilated ``(piece_index, worker_predicate,
    shuffle_row_drop_partition)`` items."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._ngram = args['ngram']

    def process(self, piece_index: int, worker_predicate=None,
                shuffle_row_drop_partition=(0, 1), epoch=0):
        piece = self._split_pieces[piece_index]
        self._begin_item(piece, piece_index, epoch, shuffle_row_drop_partition)
        if (self._ngram is not None and worker_predicate is None
                and self._transform_spec is None):
            # Columnar window path: decode the group column-wise (vectorized
            # codecs, zero per-row Python), scan valid window starts with the
            # shared vectorized scan, and publish ONE chunk instead of
            # per-window dicts — the round-4 per-row assembler stole enough
            # worker GIL time to run 3.4x slower than its indexed twin on the
            # identical workload (BENCH_r04). Predicate/transform items keep
            # the row path: both contracts are per-row here.
            try:
                chunk = self._form_window_chunk(piece,
                                                shuffle_row_drop_partition)
            except Exception as e:  # noqa: BLE001 - policy decides
                if not self._quarantine_item('decode', e):
                    raise
                return
            if chunk is not None:
                self._publish_item(chunk, ('windows', len(chunk)), len(chunk))
            else:
                self._finish_item_empty()
            return
        try:
            if worker_predicate is not None:
                rows = self._load_rows_with_predicate(piece, worker_predicate)
            else:
                cache_key = self._cache_key('rowgroup', piece)
                rows = self._cached_load(cache_key,
                                         lambda: self._load_rows(piece))
        except Exception as e:  # noqa: BLE001 - policy decides
            if not self._quarantine_item('decode', e):
                raise
            return
        offsets = self._last_offsets
        rows, offsets = self._drop_partition(rows, piece,
                                             *shuffle_row_drop_partition,
                                             offsets=offsets)
        if self._transform_spec is not None:
            rows, offsets = self._transform_rows(rows, offsets)
        if self._ngram is not None:
            rows = self._ngram.form_ngram_dicts(rows, self._transformed_schema)
            if rows:
                # windows, not rows: window k spans several source rows
                self._publish_item(rows, ('windows', len(rows)), len(rows))
            else:
                self._finish_item_empty()
            return
        if rows:
            self._publish_item(rows,
                               self._compact_selection(offsets, len(rows)),
                               len(rows))
        else:
            self._finish_item_empty()

    # -- columnar window path --------------------------------------------------

    def _load_columns(self, piece, names, preserve_scalar_nulls=False,
                      tolerant=False):
        """Read + columnar-decode ``names`` (partition columns synthesized) —
        shared by the window-chunk path and the columnar row load.

        ``tolerant``: collect cell-level codec failures and drop the failing
        rows (quarantine; sets ``self._last_offsets`` to the kept source
        offsets). The window-chunk path keeps it off — dropping rows from a
        window universe would silently shift every window after the hole, so
        NGram corruption quarantines at item granularity instead.

        ``preserve_scalar_nulls``: the ROW path's contract is decode_row's —
        a null cell is ``None``, never a NaN-holed float that an astype to
        the declared int dtype would turn into garbage. Null-bearing scalar
        columns re-decode per cell with the field's own decode semantics
        into object arrays. Scoped HERE (not in the shared
        ``_column_to_numpy``): the columnar/indexed batch paths need a
        STABLE numeric dtype per field across row groups (their assembly
        pre-allocates from the first piece), and they keep the documented
        NaN-holing arrow/pandas parity."""
        from petastorm_tpu.readers.columnar_worker import make_partition_columns
        table = self._read_columns(piece, self._stored_columns(names, piece))
        sink = self._decode_error_sink() if tolerant else None
        columns = self._decode_table(table, names, error_sink=sink)
        if preserve_scalar_nulls:
            for name in names:
                if name not in table.column_names or name not in columns:
                    continue
                column = table.column(name)
                if not column.null_count or columns[name].dtype == object:
                    continue   # object columns already carry None cells
                field = self._full_schema.fields[name]
                decode = self._decode_overrides.get(name)
                if decode is None and field.codec is not None:
                    decode = (lambda v, _f=field: _f.codec.decode(_f, v))
                elif decode is None and isinstance(field.numpy_dtype, np.dtype) \
                        and field.numpy_dtype.kind in 'biufc':
                    decode = field.numpy_dtype.type
                out = np.empty(len(column), dtype=object)
                out[:] = [None if v is None
                          else (decode(v) if decode is not None else v)
                          for v in column.to_pylist()]
                columns[name] = out
        n = table.num_rows
        offsets = self._range_offsets(n) if self._tracks_offsets else None
        if sink is not None and sink.errors:
            columns, kept = self._apply_quarantine_drops(columns, sink, n)
            offsets = kept
            n = len(kept)
        columns.update(make_partition_columns(self._full_schema, piece,
                                              n, set(names)))
        self._last_offsets = offsets
        return columns

    def _load_window_columns(self, piece):
        """Decode every field the NGram references, column-wise."""
        return self._load_columns(
            piece, [n for n in self._ngram.get_all_field_names()
                    if n in self._full_schema.fields])

    def _form_window_chunk(self, piece, shuffle_row_drop_partition):
        cache_key = self._cache_key('ngram_cols', piece)
        columns = self._cached_load(
            cache_key, lambda: self._load_window_columns(piece))
        partition, num_partitions = shuffle_row_drop_partition
        if num_partitions > 1:
            # same semantics as _drop_partition: a file-order slice, extended
            # by length-1 continuation rows so boundary-spanning windows
            # survive (sorting happens after the slice, like the row path)
            n = len(next(iter(columns.values()))) if columns else 0
            bounds = np.linspace(0, n, num_partitions + 1, dtype=int)
            start = int(bounds[partition])
            stop = min(int(bounds[partition + 1]) + self._ngram.length - 1, n)
            if stop <= start:
                return None
            columns = {k: v[start:stop] for k, v in columns.items()}
        return self._ngram.form_windows_columnar(columns)

    # -- loading ---------------------------------------------------------------

    def _planned_columns(self, piece):
        """Mirror the primary read of each no-predicate branch of
        :meth:`process` so the readahead prefetches the exact same column
        list (key equality is what turns a prefetch into a hit)."""
        if self._ngram is not None and self._transform_spec is None:
            # columnar window-chunk path (_load_window_columns)
            names = [n for n in self._ngram.get_all_field_names()
                     if n in self._full_schema.fields]
        elif self._ngram is not None:
            # ngram fallback row path (_load_rows with ngram)
            names = [n for n in self._ngram.get_all_field_names()
                     if n in self._schema.fields or n in self._full_schema.fields]
        else:
            names = list(self._schema.fields.keys())
        return self._stored_columns(names, piece)

    def _planned_cache_key(self, piece, params):
        # mirror process(): the plain-ngram branch caches decoded window
        # columns; every other no-predicate item caches decoded row dicts
        if self._ngram is not None and self._transform_spec is None:
            return self._cache_key('ngram_cols', piece)
        return self._cache_key('rowgroup', piece)

    def _read_columns(self, piece, columns: List[str]):
        return self._read_row_group(piece, columns)

    def _decode_with_partitions(self, raw_rows: List[dict], piece, schema) -> List[dict]:
        self.beat('decode')   # entry beat: a wedged codec shows as `decode`
        start = time.perf_counter()
        decoded = []
        partition_items = piece.partition_dict.items()
        for raw in raw_rows:
            for key, value in partition_items:
                field = schema.fields.get(key)
                if field is not None:
                    raw[key] = _cast_partition_value(field, value)
            decoded.append(decode_row(raw, schema, self._decode_overrides))
        elapsed = time.perf_counter() - start
        self.record_latency('decode', elapsed)
        self.record_span('decode_rows', 'decode', start, elapsed)
        return decoded

    def _load_rows(self, piece) -> List[dict]:
        if self._ngram is not None:
            # ngram fallback items (predicate/transform) still row-load the
            # full window universe; the plain ngram path ships chunks instead
            field_names = [n for n in self._ngram.get_all_field_names()
                           if n in self._schema.fields or n in self._full_schema.fields]
            table = self._read_columns(piece,
                                       self._stored_columns(field_names, piece))
            rows = self._decode_with_partitions(table.to_pylist(), piece,
                                                self._full_schema)
            self._last_offsets = (self._range_offsets(len(rows))
                                  if self._tracks_offsets else None)
            return rows
        # Row path decodes COLUMN-wise (shared _decode_table: hoisted cell
        # decoders, zero-copy cell views, vectorized scalar/list conversion)
        # and then splits into row dicts — ~2x less non-codec overhead per
        # row than to_pylist + per-row decode_row on decode-bound stores.
        names = list(self._schema.fields.keys())
        columns = self._load_columns(piece, names, preserve_scalar_nulls=True,
                                     tolerant=self._tolerant_decode)
        keys = [n for n in names if n in columns]
        cols = [columns[k] for k in keys]
        return [dict(zip(keys, values)) for values in zip(*cols)]

    def _load_rows_with_predicate(self, piece, predicate) -> List[dict]:
        """Read predicate columns first; early-exit when nothing matches
        (reference ``py_dict_reader_worker.py:188-252``)."""
        predicate_fields = predicate.get_fields()
        unknown = set(predicate_fields) - set(self._full_schema.fields.keys())
        if unknown:
            raise ValueError('Predicate uses unknown fields: {}'.format(sorted(unknown)))
        predicate_table = self._read_columns(
            piece, self._stored_columns(predicate_fields, piece))
        predicate_rows = self._decode_with_partitions(
            predicate_table.to_pylist(), piece, self._full_schema)
        match_indices = [i for i, row in enumerate(predicate_rows)
                         if predicate.do_include({f: row[f] for f in predicate_fields})]
        self._last_offsets = (np.asarray(match_indices, dtype=np.int64)
                              if self._tracks_offsets else None)
        if not match_indices:
            return []
        other_fields = [n for n in self._schema.fields.keys() if n not in predicate_fields]
        if other_fields:
            other_table = self._read_columns(
                piece, self._stored_columns(other_fields, piece)).take(match_indices)
            other_rows = self._decode_with_partitions(
                other_table.to_pylist(), piece, self._full_schema)
        else:
            other_rows = [{} for _ in match_indices]
        result = []
        for matched_at, extra in zip(match_indices, other_rows):
            row = {f: predicate_rows[matched_at][f] for f in predicate_fields
                   if f in self._schema.fields}
            row.update(extra)
            result.append(row)
        return result

    # -- post-processing -------------------------------------------------------

    def _drop_partition(self, rows: List[dict], piece, partition: int,
                        num_partitions: int, offsets=None):
        """Deterministically keep 1/num_partitions of the row group; with ngram,
        extend by length-1 continuation rows so windows spanning the boundary
        survive (reference ``py_dict_reader_worker.py:260-273``). Returns
        ``(rows, offsets)`` with the provenance offsets sliced in lockstep."""
        if num_partitions <= 1:
            return rows, offsets
        bounds = np.linspace(0, len(rows), num_partitions + 1, dtype=int)
        start, stop = bounds[partition], bounds[partition + 1]
        if self._ngram is not None:
            stop = min(stop + self._ngram.length - 1, len(rows))
        offsets = self._slice_offsets(offsets, start, stop)
        return rows[start:stop], offsets

    def _transform_rows(self, rows: List[dict], offsets):
        """Apply the TransformSpec per row; under quarantine/skip policies a
        row whose transform raises is dropped (and recorded with its exact
        source offset) instead of killing the worker."""
        if not self._tolerant_decode:
            return [self._apply_transform(r) for r in rows], offsets
        out, kept = [], []
        range_base = offsets[1] if isinstance(offsets, tuple) else None
        for i, row in enumerate(rows):
            try:
                out.append(self._apply_transform(row))
                kept.append(i)
            except NEVER_QUARANTINE:
                raise   # infrastructure failure, not a bad sample: stay loud
            except Exception as e:  # noqa: BLE001 - policy decides
                if offsets is None:
                    off = None
                elif range_base is not None:
                    off = range_base + i
                else:
                    off = int(offsets[i])
                self._quarantine_event(
                    'transform', e, rows=1,
                    row_offsets=None if off is None else [off])
        if offsets is not None and len(kept) != len(rows):
            if isinstance(offsets, tuple):
                offsets = np.arange(offsets[1], offsets[2], dtype=np.int64)
            offsets = (offsets[np.asarray(kept, dtype=np.int64)]
                       if kept else offsets[:0])
        return out, offsets

    def _apply_transform(self, row: dict) -> dict:
        spec = self._transform_spec
        if spec.func is not None:
            row = spec.func(row)
        return {name: row[name] for name in self._transformed_schema.fields if name in row}
