"""Batch-granular reader worker: publishes whole row groups as arrow tables;
the consumer converts columns to numpy arrays.

Reference parity: ``petastorm/arrow_reader_worker.py`` — worker (:90-316),
vectorized predicate (:229-288), TransformSpec on pandas with shape checks and
ravel of >1-D arrays (:172-227), partition-column handling (:290-303),
results-queue reader converting Table -> numpy dict (:38-87).
"""

from __future__ import annotations

import time
from typing import List

import numpy as np
import pyarrow as pa

from petastorm_tpu.lineage import unwrap_envelope
from petastorm_tpu.readers.piece_worker import ParquetPieceWorker


class BatchResultsReader:
    """Consumer-side: arrow Table -> namedtuple of numpy column arrays
    (``batched_output=True``)."""

    def __init__(self, schema, ngram=None, lineage=None):
        assert ngram is None, 'NGram is not supported by the batch reader'
        self._schema = schema
        self._lineage = lineage if getattr(lineage, 'enabled', False) else None
        self.last_seq = None
        self.last_row_offset = None

    @property
    def batched_output(self) -> bool:
        return True

    def read_next(self, pool):
        table, seq = unwrap_envelope(pool.get_results(), self._lineage)
        if seq is not None:
            self.last_seq = seq
        result = {}
        for name in self._schema.fields:
            if name not in table.column_names:
                continue
            column = table.column(name)
            field = self._schema.fields[name]
            result[name] = self._column_to_numpy(column, field)
        return self._schema.make_batch_namedtuple(**result)

    @staticmethod
    def _column_to_numpy(column: pa.ChunkedArray, field) -> np.ndarray:
        list_like = pa.types.is_list(column.type) or pa.types.is_large_list(column.type)
        if list_like:
            # fixed-shape numeric lists flatten in C++ (reference vstacks
            # python lists, :66-77)
            from petastorm_tpu.readers.columnar_worker import _list_column_to_numpy
            return _list_column_to_numpy(column, field)
        # string/binary columns convert in the same C++ call as numerics
        # now (an object array of str/bytes with None at nulls) — the old
        # to_pylist -> np.asarray round trip built every cell twice
        return column.to_numpy(zero_copy_only=False)


class ArrowBatchWorker(ParquetPieceWorker):
    """Processes ventilated items into published ``pa.Table`` batches."""

    def process(self, piece_index: int, worker_predicate=None,
                shuffle_row_drop_partition=(0, 1), epoch=0):
        piece = self._split_pieces[piece_index]
        self._begin_item(piece, piece_index, epoch, shuffle_row_drop_partition)
        try:
            if worker_predicate is not None:
                table = self._load_table_with_predicate(piece, worker_predicate)
            else:
                cache_key = self._cache_key('batch', piece)
                table = self._cached_load(cache_key,
                                          lambda: self._load_table(piece))
        except Exception as e:  # noqa: BLE001 - policy decides
            if not self._quarantine_item('decode', e):
                raise
            return
        offsets = self._last_offsets
        if table is None or table.num_rows == 0:
            self._finish_item_empty()
            return
        partition, num_partitions = shuffle_row_drop_partition
        if num_partitions > 1:
            bounds = np.linspace(0, table.num_rows, num_partitions + 1, dtype=int)
            table = table.slice(bounds[partition],
                                bounds[partition + 1] - bounds[partition])
            offsets = self._slice_offsets(offsets, bounds[partition],
                                          bounds[partition + 1])
        if self._transform_spec is not None:
            pre_n = table.num_rows
            try:
                table = self._apply_transform(table)
            except Exception as e:  # noqa: BLE001 - policy decides
                if not self._quarantine_item('transform', e, rows=pre_n):
                    raise
                return
            if table.num_rows != pre_n:
                offsets = None   # count-changing transform: opaque mapping
        if table.num_rows:
            self._publish_item(table,
                               self._compact_selection(offsets,
                                                       table.num_rows),
                               table.num_rows)
        else:
            self._finish_item_empty()

    # -- loading ---------------------------------------------------------------

    def _append_partition_columns(self, table: pa.Table, piece,
                                  extra_names=()) -> pa.Table:
        """Synthesize hive-partition columns for the view schema plus any
        ``extra_names`` (predicate/filter columns outside the view)."""
        from petastorm_tpu.readers.columnar_worker import make_partition_columns
        wanted = {k for k in set(self._schema.fields) | set(extra_names)
                  if k not in table.column_names}
        for key, col in make_partition_columns(self._full_schema, piece,
                                               table.num_rows, wanted).items():
            table = table.append_column(key, pa.array(col))
        return table

    def _planned_columns(self, piece):
        # the no-predicate path reads exactly _load_table's column list
        return self._stored_columns(list(self._schema.fields.keys()), piece)

    def _planned_cache_key(self, piece, params):
        return self._cache_key('batch', piece)

    def _load_table(self, piece) -> pa.Table:
        columns = self._stored_columns(list(self._schema.fields.keys()), piece)
        table = self._read_row_group(piece, columns)
        self._last_offsets = (self._range_offsets(table.num_rows)
                              if self._tracks_offsets else None)
        return self._append_partition_columns(table, piece)

    def _load_table_with_predicate(self, piece, predicate) -> pa.Table:
        """Vectorized predicate: read predicate columns, build a boolean mask,
        then read only the *remaining* columns and join them with the
        already-loaded predicate columns — each column is read exactly once
        (reference :229-288)."""
        from petastorm_tpu.readers.columnar_worker import validate_predicate_fields
        predicate_fields = validate_predicate_fields(predicate, self._full_schema)
        pred_stored = self._read_row_group(
            piece, self._stored_columns(predicate_fields, piece))
        pred_table = self._append_partition_columns(pred_stored, piece,
                                                    extra_names=set(predicate_fields))
        pred_data = {name: pred_table.column(name).to_pylist() for name in predicate_fields}
        mask = [predicate.do_include({f: pred_data[f][i] for f in predicate_fields})
                for i in range(pred_table.num_rows)]
        if not any(mask):
            self._last_offsets = None
            return None
        indices = np.nonzero(mask)[0]
        self._last_offsets = (indices.astype(np.int64)
                              if self._tracks_offsets else None)
        other_names = [n for n in self._schema.fields if n not in set(predicate_fields)]
        combined = pred_stored
        other_stored = self._stored_columns(other_names, piece)
        if other_stored:
            rest = self._read_row_group(piece, other_stored)
            for name in rest.column_names:
                combined = combined.append_column(name, rest.column(name))
        combined = self._append_partition_columns(combined, piece)
        ordered = [n for n in self._schema.fields if n in combined.column_names]
        return combined.select(ordered).take(pa.array(indices))

    # -- transform -------------------------------------------------------------

    def _apply_transform(self, table: pa.Table) -> pa.Table:
        """Run TransformSpec.func on a pandas frame; validate shapes and ravel
        >1-D ndarray cells since arrow has no ndarray columns
        (reference ``_check_shape_and_ravel``, :172-186)."""
        start = time.perf_counter()
        try:
            return self._apply_transform_impl(table)
        finally:
            elapsed = time.perf_counter() - start
            self.record_latency('decode', elapsed)
            self.record_span('transform', 'decode', start, elapsed)

    def _apply_transform_impl(self, table: pa.Table) -> pa.Table:
        spec = self._transform_spec
        df = table.to_pandas()
        if spec.func is not None:
            df = spec.func(df)
        keep = [n for n in self._transformed_schema.fields if n in df.columns]
        df = df[keep]
        for name in keep:
            field = self._transformed_schema.fields[name]
            if field.shape and len(df) and isinstance(df[name].iloc[0], np.ndarray):
                expected = tuple(field.shape)
                df[name] = df[name].map(
                    lambda a: self._check_shape_and_ravel(a, expected, name))
        return pa.Table.from_pandas(df, preserve_index=False)

    @staticmethod
    def _check_shape_and_ravel(array: np.ndarray, expected, name: str) -> np.ndarray:
        if len(array.shape) != len(expected) or any(
                e is not None and a != e for a, e in zip(array.shape, expected)):
            raise ValueError(
                'Field {!r}: transformed value shape {} does not match schema shape '
                '{}'.format(name, array.shape, expected))
        return array.ravel()
