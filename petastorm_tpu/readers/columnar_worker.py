"""Columnar decoded reader worker: the vectorized path for petastorm_tpu
(codec) datasets.

The reference forces codec datasets through a per-row path
(``petastorm/py_dict_reader_worker.py``: ``to_pylist`` -> per-row dict ->
``decode_row`` -> namedtuple), which caps Python-side throughput at tens of
thousands of rows/sec. TPU batches are columnar, so this worker decodes a row
group **column-wise**: scalar columns convert via ``Table.to_numpy`` (no
Python per row), codec columns decode cell-by-cell straight into one
preallocated ``(n, *shape)`` array, and the consumer receives a dict of
column arrays with zero per-row Python work. No reference analogue — this
path exists because the JAX adapter wants exactly this layout.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import batched_decode_enabled, split_binary_chunk
from petastorm_tpu.lineage import NEVER_QUARANTINE, unwrap_envelope
from petastorm_tpu.readers.piece_worker import ParquetPieceWorker
from petastorm_tpu.utils import cast_partition_value


class ColumnarResultsReader:
    """Consumer-side: published dict of column arrays -> batch namedtuple
    (``batched_output=True``)."""

    def __init__(self, schema, ngram=None, lineage=None):
        assert ngram is None, 'NGram is not supported by the columnar reader'
        self._schema = schema
        self._lineage = lineage if getattr(lineage, 'enabled', False) else None
        self.last_seq = None
        self.last_row_offset = None

    @property
    def batched_output(self) -> bool:
        return True

    def read_next(self, pool):
        columns, seq = unwrap_envelope(pool.get_results(), self._lineage)
        if seq is not None:
            self.last_seq = seq
        return self._schema.make_batch_namedtuple(**columns)


def _binary_cell_views(column: pa.ChunkedArray) -> list:
    """Zero-copy ``uint8`` ndarray views of every cell of a (large_)binary
    column; ``None`` for null cells.

    Slicing arrow's offsets+data buffers directly replaces ``to_pylist()``,
    which materializes a python ``bytes`` copy per cell — measurable per-cell
    overhead in decode-bound pipelines. The views keep the arrow buffer alive
    via their ``base`` reference."""
    cells = []
    for chunk in column.chunks:
        n = len(chunk)
        if not n:
            continue
        offsets, data = split_binary_chunk(chunk)
        if chunk.null_count:
            valid = chunk.is_valid().to_numpy(zero_copy_only=False)
            cells.extend(
                data[offsets[i]:offsets[i + 1]] if valid[i] else None
                for i in range(n))
        else:
            cells.extend(data[lo:hi]
                         for lo, hi in zip(offsets[:-1], offsets[1:]))
    return cells


def _decode_column_batched(column: pa.ChunkedArray, field,
                           n: int) -> Optional[np.ndarray]:
    """One-call-per-chunk vectorized decode via the codec's
    ``make_column_decoder``, or ``None`` to punt to the per-cell loop.

    Per the batched contract (``docs/decode.md``) this path only runs for
    fixed-shape fields on null-free columns; any chunk the codec cannot
    vectorize (or that raises — corrupt cells included) punts the WHOLE
    column, so error/quarantine semantics stay exactly the per-cell
    loop's."""
    make = getattr(field.codec, 'make_column_decoder', None)
    if make is None:
        return None
    decode_chunk = make(field)
    if decode_chunk is None:
        return None
    parts = []
    for chunk in column.chunks:
        if not len(chunk):
            continue
        try:
            part = decode_chunk(chunk)
        except NEVER_QUARANTINE:
            raise   # infrastructure failure, not a bad sample: stay loud
        except Exception:  # noqa: BLE001 - per-cell retry owns the error
            return None
        if part is None:
            return None
        parts.append(part)
    if not parts:
        return None
    if len(parts) > 1:
        first = parts[0]
        if any(p.dtype != first.dtype or p.shape[1:] != first.shape[1:]
               for p in parts[1:]):
            # cross-chunk geometry drift: the per-cell dense loop would
            # fail its assignment — let it own that failure
            return None
        out = np.concatenate(parts)
    else:
        out = parts[0]
    return out if len(out) == n else None


def _decode_binary_column(column: pa.ChunkedArray, field,
                          decode_override=None,
                          on_cell_error=None, batched=True,
                          path_counts=None) -> np.ndarray:
    """Decode a codec-encoded binary column into (n, *shape) (fixed shapes)
    or an object array (wildcard shapes, null cells, non-ndarray payloads).

    The row-group-vectorized path runs first (``batched``, default on):
    fixed-shape, null-free, non-overridden columns decode through the
    codec's ``make_column_decoder`` — one numpy/pyarrow call per column
    chunk instead of N Python calls. Columns the codec cannot vectorize
    (and any chunk that raises) fall back to the per-cell loop below,
    which owns the exact error/quarantine semantics; ``path_counts``
    (``{'batched': int, 'percell': int}``) records which path decoded how
    many cells, feeding the ``rows_decoded_batched``/``rows_decoded_percell``
    counters.

    On the per-cell path, cells reach the decoder as zero-copy buffer views
    and the callable comes from ``codec.make_cell_decoder`` (per-column
    setup hoisted out of the loop) — the two halves of keeping this loop
    pure decode.

    ``on_cell_error`` (bad-sample quarantine, see
    :mod:`petastorm_tpu.lineage`): instead of a corrupt cell killing the
    worker, the column is re-decoded tolerantly — every failing cell is
    reported as ``on_cell_error(row_offset, exc)`` and decodes to ``None``
    in an object array; the caller drops those rows and re-densifies. The
    dense fast path runs first, so clean columns pay nothing."""
    n = len(column)
    fixed = field.shape is not None and all(s is not None for s in field.shape)
    if not n:
        if fixed:
            return np.empty((0,) + tuple(field.shape), dtype=field.numpy_dtype)
        return np.empty(0, dtype=object)
    if (batched and decode_override is None and fixed
            and column.null_count == 0 and field.codec is not None):
        out = _decode_column_batched(column, field, n)
        if out is not None:
            if path_counts is not None:
                path_counts['batched'] += n
            return out
    if path_counts is not None:
        path_counts['percell'] += n
    decode = decode_override or field.codec.make_cell_decoder(field)
    cells = _binary_cell_views(column)
    if on_cell_error is not None:
        try:
            return _decode_cells(cells, decode, n, fixed, column.null_count)
        except NEVER_QUARANTINE:
            raise   # infrastructure failure, not a bad sample: stay loud
        except Exception:
            out = np.empty(n, dtype=object)
            failed = False
            for i, cell in enumerate(cells):
                if cell is None:
                    out[i] = None
                    continue
                try:
                    out[i] = decode(cell)
                except NEVER_QUARANTINE:
                    raise
                except Exception as e:  # noqa: BLE001 - reported, row dropped
                    failed = True
                    on_cell_error(i, e)
                    out[i] = None
            if not failed:
                # every cell decoded cleanly on retry: the dense-path failure
                # was NOT a per-cell decode error (e.g. a codec returning a
                # wrong-shaped array breaking dense assignment) — silently
                # publishing an object column would hide it; re-raise so the
                # item-level policy sees the real exception
                raise
            return out
    return _decode_cells(cells, decode, n, fixed, column.null_count)


def _decode_cells(cells, decode, n: int, fixed: bool,
                  null_count: int) -> np.ndarray:
    """The dense/object decode loops shared by the fast and tolerant paths."""
    if fixed and null_count == 0:
        first = decode(cells[0])
        if isinstance(first, np.ndarray):
            out = np.empty((n,) + first.shape, dtype=first.dtype)
        else:
            # non-ndarray payload (e.g. a bytes ScalarCodec): object column,
            # with the already-decoded first element reused
            out = np.empty(n, dtype=object)
        out[0] = first
        for i in range(1, n):
            out[i] = decode(cells[i])
        return out
    # nulls present or wildcard shape: dense packing impossible
    out = np.empty(n, dtype=object)
    for i, cell in enumerate(cells):
        out[i] = None if cell is None else decode(cell)
    return out


def _list_column_to_numpy(column: pa.ChunkedArray, field) -> np.ndarray:
    """List column -> numpy. Fixed-shape numeric lists take the zero-Python
    path: flatten the arrow values buffer in C++ and reshape."""
    shape = tuple(field.shape) if field.shape else ()
    fixed = shape and all(s is not None for s in shape)
    if fixed and column.null_count == 0:
        arr = column.combine_chunks()
        flat = arr.flatten().to_numpy(zero_copy_only=False)
        if field.numpy_dtype is not None:
            target = np.dtype(field.numpy_dtype)
            if flat.dtype != target and flat.dtype.kind in 'biuf':
                flat = flat.astype(target)
        expected = len(arr) * int(np.prod(shape))
        if flat.size == expected:
            return flat.reshape((len(arr),) + shape)
        # ragged data under a fixed-shape schema: fall through to python path
    rows = column.to_pylist()
    if fixed:
        return np.asarray(rows, dtype=field.numpy_dtype).reshape(
            (len(rows),) + shape)
    out = np.empty(len(rows), dtype=object)
    for i, r in enumerate(rows):
        out[i] = np.asarray(r)
    return out


def _column_to_numpy(column: pa.ChunkedArray, field,
                     decode_override=None, on_cell_error=None,
                     batched=None, path_counts=None) -> np.ndarray:
    """Decoded numpy column for any unischema field. ``on_cell_error``
    enables tolerant codec decode (see :func:`_decode_binary_column`);
    vectorized scalar/list conversions cannot isolate cells and fail
    whole-column under every policy. ``batched``/``path_counts`` gate and
    observe the row-group-vectorized codec path; the default (``None``)
    consults the ``PETASTORM_TPU_BATCHED_DECODE`` switch per call, so
    every caller honors the kill switch — workers pass their
    construction-time read explicitly to keep the env lookup off the
    per-column hot path."""
    if batched is None:
        batched = batched_decode_enabled()
    if field.codec is not None and (
            pa.types.is_binary(column.type) or pa.types.is_large_binary(column.type)):
        return _decode_binary_column(column, field, decode_override,
                                     on_cell_error=on_cell_error,
                                     batched=batched,
                                     path_counts=path_counts)
    if pa.types.is_list(column.type) or pa.types.is_large_list(column.type):
        return _list_column_to_numpy(column, field)
    if pa.types.is_string(column.type) or pa.types.is_large_string(column.type):
        # one C++ conversion instead of a to_pylist -> np.asarray round
        # trip; both produce an object array of str with None at nulls
        return column.to_numpy(zero_copy_only=False)
    arr = column.to_numpy(zero_copy_only=False)
    if field.numpy_dtype is not None and not field.shape:
        try:
            target = np.dtype(field.numpy_dtype)
        except TypeError:
            return arr
        # null-bearing numeric columns stay NaN-holed floats (pandas/arrow
        # parity — the documented batched-path semantics); an astype to a
        # declared int dtype would mint garbage where the nulls were. The
        # row reader re-decodes such columns per cell with None preserved
        # (row_worker._load_columns).
        if (arr.dtype != target and arr.dtype.kind not in ('O', 'U', 'S')
                and not (column.null_count and target.kind in 'biu')):
            arr = arr.astype(target)
    return arr


def validate_predicate_fields(predicate, schema) -> list:
    """Predicate field names, validated against ``schema`` (the FULL stored
    schema — predicates may use fields outside the reader's output view)."""
    fields = list(predicate.get_fields())
    unknown = set(fields) - set(schema.fields.keys())
    if unknown:
        raise ValueError('Predicate uses unknown fields: {}'.format(
            sorted(unknown)))
    return fields


def make_partition_columns(schema, piece, n: int, names) -> Dict[str, np.ndarray]:
    """Synthesize hive-partition-derived columns (constant per piece) for the
    requested ``names``, typed per ``schema`` when the field is declared."""
    out = {}
    for key, value in piece.partition_dict.items():
        if key in names:
            field = schema.fields.get(key)
            typed = cast_partition_value(
                field.numpy_dtype if field is not None else None, value)
            if isinstance(typed, str):
                col = np.empty(n, dtype=object)
                col[:] = typed
            else:
                col = np.full(n, typed)
            out[key] = col
    return out


def _code_digest(code) -> str:
    """Digest of a code object covering bytecode AND constants (constants
    live in ``co_consts``, not ``co_code`` — editing ``x*2`` to ``x*3``
    changes only the former), recursing into nested code objects whose repr
    would otherwise embed unstable memory addresses."""
    parts = [code.co_code.hex()]
    for const in code.co_consts:
        if hasattr(const, 'co_code'):
            parts.append(_code_digest(const))
        else:
            parts.append(repr(const))
    return '|'.join(parts)


def _stable_value_digest(value) -> str:
    """Value identity that does not truncate: ndarrays hash their full bytes
    (``repr`` elides middle elements of large arrays, which would collide
    distinct normalization tables), recursing through list/tuple/dict
    containers so a captured ``[lut_array]`` is covered too; everything else
    uses repr."""
    if isinstance(value, np.ndarray):
        import hashlib
        h = hashlib.md5(np.ascontiguousarray(value).tobytes())
        return 'ndarray:{}:{}:{}'.format(value.dtype, value.shape,
                                         h.hexdigest())
    if isinstance(value, (list, tuple)):
        return '{}[{}]'.format(type(value).__name__,
                               ','.join(_stable_value_digest(v)
                                        for v in value))
    if isinstance(value, dict):
        return 'dict{{{}}}'.format(','.join(
            '{}:{}'.format(repr(k), _stable_value_digest(v))
            for k, v in sorted(value.items(), key=lambda kv: repr(kv[0]))))
    return repr(value)


def transform_fingerprint(spec) -> str:
    """Best-effort identity of a TransformSpec for cache keying: the func's
    qualified name + code (bytecode, constants, positional AND keyword-only
    defaults, closure values) + declared schema edits. Catches logic,
    constant, default-arg, and field-list edits; mutated closure OBJECTS
    whose repr doesn't change remain invisible (caveat — pass a fresh
    ``cache_location`` when parameterizing a transform through mutable
    closure state)."""
    import hashlib
    func = spec.func
    parts = []
    if func is not None:
        code = getattr(func, '__code__', None)
        kwdefaults = getattr(func, '__kwdefaults__', None) or {}
        parts.extend([getattr(func, '__module__', ''),
                      getattr(func, '__qualname__', repr(func)),
                      _code_digest(code) if code is not None else '',
                      '|'.join(_stable_value_digest(v) for v in
                               (getattr(func, '__defaults__', None) or ())),
                      '|'.join('{}={}'.format(k, _stable_value_digest(v))
                               for k, v in sorted(kwdefaults.items()))])
        closure = getattr(func, '__closure__', None) or ()
        parts.extend(_stable_value_digest(getattr(cell, 'cell_contents', None))
                     for cell in closure)
    parts.append(repr([(f.name, str(f.numpy_dtype), f.shape)
                       for f in (spec.edit_fields or [])]))
    parts.append(repr(sorted(spec.removed_fields or [])))
    parts.append(repr(sorted(spec.selected_fields or [])))
    return hashlib.md5('|'.join(parts).encode()).hexdigest()[:16]


def predicate_row_mask(predicate, fields, cols, n: int) -> np.ndarray:
    """Boolean include-mask from ``predicate`` over decoded columns.

    Predicates exposing a ``column_mask`` hook (e.g. the common
    single-field :class:`~petastorm_tpu.predicates.in_set` membership)
    evaluate in one vectorized numpy call; the hook returns ``None`` for
    column dtypes where numpy equality could diverge from Python's (object
    columns, NaN members), and generic predicates without the hook keep
    the per-row dict path."""
    column_mask = getattr(predicate, 'column_mask', None)
    if column_mask is not None:
        mask = column_mask(cols)
        if mask is not None:
            return np.asarray(mask, dtype=bool)
    return np.fromiter(
        (bool(predicate.do_include({f: cols[f][i] for f in fields}))
         for i in range(n)), dtype=bool, count=n)


class ColumnarWorker(ParquetPieceWorker):
    """Processes ventilated items into published dicts of decoded numpy
    column arrays."""

    #: The columnar publish path ships dicts of per-column arrays, so a
    #: device-planned column can travel as its raw ``(n, stride)`` uint8
    #: grid (docs/decode.md "Device-side decode"). Row/arrow-batch workers
    #: leave this unset and the reader's planner declines for them.
    supports_device_decode = True

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        # the spec is fixed for the worker's lifetime: fingerprint once, not
        # per row group per epoch
        self._transform_key = (
            transform_fingerprint(self._transform_spec)
            if self._transform_spec is not None else None)

    def process(self, piece_index: int, worker_predicate=None,
                shuffle_row_drop_partition=(0, 1), epoch=0):
        piece = self._split_pieces[piece_index]
        partition, num_partitions = shuffle_row_drop_partition
        self._begin_item(piece, piece_index, epoch, shuffle_row_drop_partition)
        try:
            if (worker_predicate is None and num_partitions == 1
                    and self._transform_spec is not None):
                # Cache POST-transform (the reference's batch-path semantics:
                # ``arrow_reader_worker.py:195-227`` applies the TransformSpec
                # inside the load the cache wraps): epochs 2+ skip BOTH codec
                # decode and the transform, and a shrinking transform (e.g.
                # image resize) shrinks the cache payload with it. The key
                # carries a best-effort transform fingerprint (code bytes +
                # schema edits) so editing the transform invalidates entries.
                cache_key = self._cache_key(
                    'columnar_tx:' + self._transform_key, piece)
                columns = self._cached_load(
                    cache_key, lambda: self._apply_transform(self._load(piece)))
                if columns and len(next(iter(columns.values()))):
                    n = len(next(iter(columns.values())))
                    # a transform may change the row count arbitrarily, so
                    # delivered rows cannot be mapped back to source offsets
                    self._publish_item(columns, ('opaque', n), n)
                else:
                    self._finish_item_empty()
                return
            if worker_predicate is not None:
                columns = self._load_with_predicate(piece, worker_predicate)
            else:
                cache_key = self._cache_key('columnar', piece)
                columns = self._cached_load(cache_key,
                                            lambda: self._load(piece))
        except Exception as e:  # noqa: BLE001 - policy decides
            if not self._quarantine_item('decode', e):
                raise
            return
        offsets = self._last_offsets
        if columns is None:
            self._finish_item_empty()
            return
        n = len(next(iter(columns.values()))) if columns else 0
        if not n:
            self._finish_item_empty()
            return
        if num_partitions > 1:
            bounds = np.linspace(0, n, num_partitions + 1, dtype=int)
            lo, hi = bounds[partition], bounds[partition + 1]
            columns = {k: v[lo:hi] for k, v in columns.items()}
            offsets = self._slice_offsets(offsets, lo, hi)
            if hi <= lo:
                self._finish_item_empty()
                return
            n = int(hi - lo)
        if self._transform_spec is not None:
            try:
                columns = self._apply_transform(columns)
            except Exception as e:  # noqa: BLE001 - policy decides
                if not self._quarantine_item('transform', e, rows=n):
                    raise
                return
            if not columns or not len(next(iter(columns.values()))):
                self._finish_item_empty()
                return
            post_n = len(next(iter(columns.values())))
            if post_n != n:
                offsets = None   # count-changing transform: opaque mapping
            n = post_n
        self._publish_item(columns, self._compact_selection(offsets, n), n)

    # -- loading ---------------------------------------------------------------

    # _decode_table comes from ParquetPieceWorker (shared with the row
    # worker's columnar window path)

    def _partition_columns(self, piece, n: int, names) -> Dict[str, np.ndarray]:
        return make_partition_columns(self._full_schema, piece, n, names)

    def _planned_columns(self, piece):
        # every no-predicate branch of process() funnels through _load()
        return self._stored_columns(list(self._schema.fields.keys()), piece)

    def _planned_cache_key(self, piece, params):
        # mirror process(): whole-group transform items cache post-transform
        partition = params.get('shuffle_row_drop_partition', (0, 1))
        if self._transform_spec is not None and partition[1] == 1:
            return self._cache_key('columnar_tx:' + self._transform_key,
                                   piece)
        return self._cache_key('columnar', piece)

    def _load(self, piece) -> Dict[str, np.ndarray]:
        names = list(self._schema.fields.keys())
        table = self._read_row_group(piece, self._stored_columns(names, piece))
        sink = self._decode_error_sink()
        columns = self._decode_table(table, names, error_sink=sink)
        n = table.num_rows
        offsets = self._range_offsets(n) if self._tracks_offsets else None
        if sink is not None and sink.errors:
            columns, kept = self._apply_quarantine_drops(columns, sink, n)
            offsets = kept
            n = len(kept)
        columns.update(self._partition_columns(piece, n, set(names)))
        self._last_offsets = offsets
        return columns

    def _load_with_predicate(self, piece, predicate) -> Optional[Dict[str, np.ndarray]]:
        """Decode predicate columns first; decode the remaining columns only at
        matching indices (cheaper than the row path, which decodes entire
        predicate rows eagerly)."""
        predicate_fields = validate_predicate_fields(predicate, self._full_schema)
        pred_table = self._read_row_group(
            piece, self._stored_columns(predicate_fields, piece))
        pred_cols = self._decode_table(pred_table, predicate_fields)
        pred_cols.update(self._partition_columns(
            piece, pred_table.num_rows, set(predicate_fields)))
        n = pred_table.num_rows
        mask = predicate_row_mask(predicate, predicate_fields, pred_cols, n)
        if not mask.any():
            return None
        idx = np.nonzero(mask)[0]
        out = {f: pred_cols[f][idx] for f in predicate_fields
               if f in self._schema.fields}
        other = [f for f in self._schema.fields if f not in set(predicate_fields)]
        other_stored = self._stored_columns(other, piece)
        if other_stored:
            rest = self._read_row_group(piece, other_stored)
            rest = rest.take(pa.array(idx))
            out.update(self._decode_table(rest, other_stored))
        out.update(self._partition_columns(piece, len(idx), set(other)))
        self._last_offsets = (idx.astype(np.int64)
                              if self._tracks_offsets else None)
        return out

    # -- transform -------------------------------------------------------------

    def _apply_transform(self, columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """TransformSpec over a dict of column arrays (the columnar-path
        contract; the row path hands ``func`` one row dict at a time, the arrow
        batch path a pandas frame)."""
        from petastorm_tpu.transform import apply_columnar_transform
        start = time.perf_counter()
        out = apply_columnar_transform(self._transform_spec,
                                       self._transformed_schema, columns)
        elapsed = time.perf_counter() - start
        self.record_latency('decode', elapsed)
        self.record_span('transform', 'decode', start, elapsed)
        return out
