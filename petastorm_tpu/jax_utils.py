"""JAX/TPU adapter: turn a Reader into an iterator of device-ready batches.

This replaces the reference's framework adapters (``petastorm/tf_utils.py``,
``petastorm/pytorch.py``) with a TPU-first design:

- Host side: rows/batches from the reader are sanitized to numpy, optionally
  shuffled in a (batched) shuffling buffer, and assembled into fixed-size
  column batches — all zero-copy where pyarrow/numpy allow.
- Device side: ``make_jax_loader(..., mesh=...)`` builds global
  ``jax.Array``s with ``jax.make_array_from_process_local_data`` over a
  GSPMD mesh (each TPU host feeds only its own shard — the multi-host story
  the reference delegated to Horovod env vars,
  ``spark_dataset_converter.py:122-159``), and ``prefetch_to_device``
  double-buffers host→HBM transfers so infeed overlaps compute (replacing
  the reference's ``tf.py_func``/queue infeed, ``tf_utils.py:202-252``).

Dtype policy (reference analogue ``tf_utils.py:27-44`` / ``pytorch.py:41-71``):
JAX handles the full unsigned/bool range natively, so no uint16/uint32
promotion is needed. Decimals are cast to float64; datetime64 to int64
nanoseconds; strings/objects stay host-only and are excluded from device
transfer unless the caller handles them.
"""

import collections
import logging
import os
import threading
import time
from decimal import Decimal

import numpy as np

from petastorm_tpu.goodput import GoodputMonitor, goodput_enabled
from petastorm_tpu.lineage import (LINEAGE_COLUMN, PACK_SHIFT, PROVENANCE_KEY,
                                   BatchProvenance, pack_rows)
from petastorm_tpu.readers.shuffling_buffer import (
    BatchedNoopShufflingBuffer, BatchedRandomShufflingBuffer,
    NoopShufflingBuffer, RandomShufflingBuffer)

logger = logging.getLogger(__name__)

_DEVICE_INCOMPATIBLE_KINDS = ('U', 'S', 'O')  # unicode, bytes, python objects


def _sanitize_value(value):
    """Make a single field value numpy-native and JAX-friendly."""
    if isinstance(value, Decimal):
        return np.float64(value)
    value = np.asarray(value)
    if value.dtype.kind == 'M':  # datetime64 -> int64 ns since epoch
        return value.astype('datetime64[ns]').astype(np.int64)
    if value.dtype.kind == 'O' and value.size and isinstance(value.flat[0], Decimal):
        return value.astype(np.float64)
    return value


def sanitize_jax_types(row_dict):
    """In-place dtype sanitization of a row/batch dict for JAX consumption."""
    for name, value in row_dict.items():
        row_dict[name] = _sanitize_value(value)
    return row_dict


def _is_device_compatible(arr):
    return getattr(arr, 'dtype', np.dtype(object)).kind not in _DEVICE_INCOMPATIBLE_KINDS


def _contiguous_rows_view(vals):
    """Zero-copy batch assembly: when ``vals`` are consecutive row views of
    one dense ``(n, *shape)`` decoded column (the unshuffled row-stream
    case — worker columns split into row dicts, consumed in order), the
    batch is a contiguous range of that column and one slice replaces the
    per-row ``np.stack`` memcpy. Returns ``None`` whenever that cannot be
    proven — shuffled rows, process-pool reconstructed rows, scalar or
    object cells — and the caller keeps the copying path. The slice shares
    the column's memory (and its writability): treat collated batches as
    read-only, as ``docs/decode.md`` documents."""
    if not len(vals):
        return None
    first = vals[0]
    base = first.base
    if base is None or not isinstance(base, np.ndarray) or first.ndim == 0:
        return None
    if (base.ndim != first.ndim + 1 or base.shape[1:] != first.shape
            or base.dtype != first.dtype
            or base.dtype.kind in _DEVICE_INCOMPATIBLE_KINDS):
        return None
    row_bytes = base.strides[0]
    if row_bytes <= 0 or first.strides != base.strides[1:]:
        return None
    base_ptr = base.__array_interface__['data'][0]
    ptr = first.__array_interface__['data'][0]
    start, rem = divmod(ptr - base_ptr, row_bytes)
    if rem or start < 0 or start + len(vals) > base.shape[0]:
        return None
    for v in vals[1:]:
        ptr += row_bytes
        if (v.base is not base or v.shape != first.shape
                or v.dtype != first.dtype or v.strides != first.strides
                or v.__array_interface__['data'][0] != ptr):
            return None
    return base[start:start + len(vals)]


#: Environment default for the device-prefetch window
#: (:func:`prefetch_to_device` / :func:`prefetch_batches` ``size`` and the
#: loaders' ``prefetch_depth`` knob). Unset means :data:`DEFAULT_PREFETCH_DEPTH`.
PREFETCH_DEPTH_ENV_VAR = 'PETASTORM_TPU_PREFETCH_DEPTH'

#: Double-buffering: stage batch N+1 while batch N computes. Depths beyond
#: 2-4 only pay off when step times are highly variable (docs/readahead.md).
DEFAULT_PREFETCH_DEPTH = 2


def resolve_prefetch_depth(depth):
    """Validated prefetch depth: the explicit knob wins, then
    :data:`PREFETCH_DEPTH_ENV_VAR`, then :data:`DEFAULT_PREFETCH_DEPTH`."""
    if depth is None:
        raw = os.environ.get(PREFETCH_DEPTH_ENV_VAR, '').strip()
        if not raw:
            return DEFAULT_PREFETCH_DEPTH
        depth = raw
    if isinstance(depth, float):
        # int() would silently truncate 2.5 -> 2; a fractional depth is a
        # caller bug worth surfacing
        raise ValueError('prefetch depth must be an integer >= 1, got {!r}'
                         .format(depth))
    try:
        depth = int(depth)
    except (TypeError, ValueError):
        raise ValueError('prefetch depth must be an integer >= 1, got {!r}'
                         .format(depth))
    if depth < 1:
        raise ValueError('prefetch depth must be >= 1, got {}'.format(depth))
    return depth


def validate_pad_spec(pad_spec):
    """Normalize/validate a ragged-padding spec at loader construction.

    ``pad_spec`` maps field name -> ``{'buckets': [n1, n2, ...]}`` or
    ``{'max_len': n}``, plus optional ``'pad_value'`` (default 0),
    ``'length_field'`` (default ``'<name>_len'``), ``'dtype'`` and
    ``'trailing_shape'``. The last two only matter for ZERO-row batches,
    where neither can be inferred from data; declaring them keeps empty
    batches dtype/rank-identical to non-empty ones (without them an empty
    batch falls back to ``pad_value``'s dtype and no trailing dims)."""
    if not pad_spec:
        return None
    normalized = {}
    for name, spec in pad_spec.items():
        spec = dict(spec)
        buckets = spec.pop('buckets', None)
        max_len = spec.pop('max_len', None)
        pad_value = spec.pop('pad_value', 0)
        length_field = spec.pop('length_field', name + '_len')
        dtype = spec.pop('dtype', None)
        trailing_shape = spec.pop('trailing_shape', ())
        if spec:
            raise ValueError('pad_spec for {!r} has unknown keys {}'.format(
                name, sorted(spec)))
        if (buckets is None) == (max_len is None):
            raise ValueError("pad_spec for {!r} needs exactly one of "
                             "'buckets' or 'max_len'".format(name))
        if buckets is None:
            buckets = [max_len]
        buckets = sorted(int(b) for b in buckets)
        if not buckets or buckets[0] <= 0:
            raise ValueError('pad_spec buckets for {!r} must be positive '
                             'ints, got {!r}'.format(name, buckets))
        normalized[name] = {'buckets': buckets, 'pad_value': pad_value,
                            'length_field': length_field,
                            'dtype': None if dtype is None else np.dtype(dtype),
                            'trailing_shape': tuple(trailing_shape)}
    return normalized


def check_pad_spec_fields(pad_spec, field_names, who: str) -> None:
    """Validate a NORMALIZED pad_spec against a schema's field names: every
    padded field must exist (a typo must fail, not silently no-op) and no
    ``length_field`` may collide with a real column
    (:func:`pad_ragged_batch` would silently overwrite its data). Shared by
    the streaming and indexed loaders."""
    if not pad_spec:
        return
    names = set(field_names)
    unknown = set(pad_spec) - names
    if unknown:
        raise ValueError('{}: pad_spec names unknown fields {} (schema has '
                         '{})'.format(who, sorted(unknown), sorted(names)))
    for name, spec in pad_spec.items():
        if spec['length_field'] in names:
            raise ValueError(
                "{}: pad_spec length_field {!r} for {!r} collides with an "
                'existing column; pick another via length_field='.format(
                    who, spec['length_field'], name))


def require_single_bucket_pad_spec(pad_spec, loader_name: str) -> None:
    """Sharded loaders pad each host's LOCAL sub-batch: with multiple
    buckets, hosts can disagree on the padded width of the same global step
    and ``make_array_from_process_local_data`` would assemble inconsistent
    global shapes (multi-host hang). Shared by the streaming and indexed
    sharded loaders."""
    if not pad_spec:
        return
    multi = {n for n, s in pad_spec.items() if len(s['buckets']) > 1}
    if multi:
        raise ValueError(
            "{} needs a single-bucket pad_spec (use 'max_len'); fields "
            'with multiple buckets: {}'.format(loader_name, sorted(multi)))


def pad_ragged_batch(batch, pad_spec):
    """Pad ragged (object-dtype) columns into dense bucketed arrays so
    variable-length fields can live in HBM under jit.

    For each spec'd field, rows are padded along their first dimension to the
    smallest bucket covering the batch's longest row, and the true lengths are
    emitted as an int32 ``length_field`` column (build masks from it on
    device). Bucketing bounds XLA recompilation to ``len(buckets)`` shapes —
    the pad-to-bucket answer to the static-shape-vs-ragged-fields problem
    (SURVEY §7 "hard parts"). Already-dense columns pass through with a
    constant length column for API uniformity."""
    out = dict(batch)
    for name, spec in pad_spec.items():
        col = out.get(name)
        if col is None:
            continue
        if not (isinstance(col, np.ndarray) and col.dtype == object):
            # Dense arrival (all rows equal length — always true at
            # batch_size=1) must STILL pad to a bucket, or every distinct
            # length is a fresh XLA compile and the bucket-width promise is
            # broken.
            col = np.asarray(col)
            if col.ndim < 2:
                raise ValueError('pad_spec field {!r} has scalar rows; '
                                 'padding needs at least one dimension'
                                 .format(name))
            width = col.shape[1]
            bucket = next((b for b in spec['buckets'] if b >= width), None)
            if bucket is None:
                raise ValueError(
                    'pad_spec field {!r}: row length {} exceeds largest '
                    'bucket {}'.format(name, width, spec['buckets'][-1]))
            if bucket != width:
                padded = np.full((len(col), bucket) + col.shape[2:],
                                 spec['pad_value'], dtype=col.dtype)
                padded[:, :width] = col
                col = padded
            out[name] = col
            out[spec['length_field']] = np.full(len(col), width, np.int32)
            continue
        rows = [np.asarray(v) for v in col]
        if not rows:
            # Empty batch: emit an empty dense column at the smallest bucket
            # so shapes stay bucket-stable even for zero-row batches. dtype
            # and trailing dims can't be inferred from zero rows — they come
            # from the spec's 'dtype'/'trailing_shape' declarations when
            # batch-shape stability across the empty case matters.
            bucket = spec['buckets'][0]
            dtype = spec['dtype']
            if dtype is None:
                dtype = np.asarray(spec['pad_value']).dtype
            shape = (0, bucket) + spec['trailing_shape']
            out[name] = np.empty(shape, dtype=dtype)
            out[spec['length_field']] = np.empty((0,), np.int32)
            continue
        if any(r.ndim < 1 for r in rows):
            raise ValueError('pad_spec field {!r} has scalar rows; padding '
                             'needs at least one dimension'.format(name))
        lengths = np.asarray([len(r) for r in rows], np.int32)
        longest = int(lengths.max())
        bucket = next((b for b in spec['buckets'] if b >= longest), None)
        if bucket is None:
            raise ValueError(
                'pad_spec field {!r}: row length {} exceeds largest bucket {}'
                .format(name, longest, spec['buckets'][-1]))
        first = rows[0]
        dense = np.full((len(rows), bucket) + first.shape[1:],
                        spec['pad_value'], dtype=first.dtype)
        for i, r in enumerate(rows):
            dense[i, :len(r)] = r
        out[name] = dense
        out[spec['length_field']] = lengths
    return out


class JaxLoaderBase(object):
    """Iteration-state guard + auto-reset, mirroring the reference's
    ``LoaderBase`` (``pytorch.py:104-129``)."""

    def __init__(self, reader):
        self.reader = reader
        self._in_iter = None
        self._error = None
        #: The reader pool's :class:`~petastorm_tpu.tracing.Tracer` (None
        #: when tracing is off). The iteration loop records ``infeed_wait``
        #: (time producing the next batch) and ``train_step`` (the consumer's
        #: gap between batches) spans into it, so the device-idle gap is
        #: visible on the same timeline as the worker stages.
        self.tracer = getattr(reader, 'tracer', None)
        #: The reader's :class:`~petastorm_tpu.health.HealthMonitor` (None
        #: for readers without one). Pass it to ``prefetch_to_device(...,
        #: health=loader.health)`` so the prefetch thread heartbeats onto the
        #: same watchdog as the rest of the pipeline.
        self.health = getattr(reader, 'health', None)
        #: The reader pool's ``ReaderStats`` (None for readers without one).
        #: When its latency plane is on, the iteration loop records
        #: ``infeed_wait``/``train_step`` duration histograms even with
        #: tracing off — tail latencies must not require a span ring.
        self.stats = getattr(reader, 'stats', None)
        #: Background lookahead window for :meth:`iter_prefetched`; subclass
        #: constructors overwrite it from their ``prefetch_depth`` knob.
        self.prefetch_depth = resolve_prefetch_depth(None)
        #: Per-step goodput accounting
        #: (:class:`~petastorm_tpu.goodput.GoodputMonitor`, None under
        #: ``PETASTORM_TPU_GOODPUT=0``). The iteration loop feeds it every
        #: step's ``infeed_wait``/train wall; the staging helpers feed it the
        #: H2D dispatch time. Call ``loader.goodput.fence(outputs)`` inside
        #: the step for the exact device/host split (docs/goodput.md).
        self.goodput = (GoodputMonitor(stats=self.stats, tracer=self.tracer)
                        if goodput_enabled() else None)
        register = getattr(reader, 'register_goodput', None)
        if register is not None and self.goodput is not None:
            register(self.goodput)

    def iter_prefetched(self, sharding=None, to_device=True):
        """Iterate with a background lookahead of ``self.prefetch_depth``
        batches: :func:`prefetch_to_device` when ``to_device`` (explicit
        per-batch ``jax.device_put``, overlapping the H2D DMA with compute),
        else :func:`prefetch_batches` (host lookahead; the jitted step's own
        call transfers). The depth is a loader knob — set it at construction
        (``prefetch_depth=``), via ``PETASTORM_TPU_PREFETCH_DEPTH``, or by
        assigning ``loader.prefetch_depth`` before calling this
        (docs/readahead.md documents who owns the knob)."""
        if to_device:
            return prefetch_to_device(iter(self), self.prefetch_depth,
                                      sharding=sharding, stats=self.stats,
                                      tracer=self.tracer, health=self.health,
                                      goodput=self.goodput)
        return prefetch_batches(iter(self), self.prefetch_depth,
                                health=self.health, stats=self.stats)

    def __iter__(self):
        if self._error is not None:
            raise RuntimeError('Cannot start a new iteration after a failed one') \
                from self._error
        if self._in_iter is not None and self._in_iter:
            raise RuntimeError('Loader is already being iterated')
        if self._in_iter is not None and not self._cache_hot():
            self.reader.reset()
            logger.warning('Start a new pass of the Reader. To avoid I/O, consider '
                           'in-memory caching (inmemory_cache_all=True).')
        self._in_iter = True
        tracer = self.tracer
        goodput = self.goodput
        latency = getattr(self.stats, 'latency', None) \
            if self.stats is not None else None
        try:
            if tracer is None and latency is None and goodput is None:
                for batch in self._iter_impl():
                    yield batch
            else:
                it = self._iter_impl()
                fetch_start = time.perf_counter()
                while True:
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    now = time.perf_counter()
                    if latency is not None:
                        latency.record('infeed_wait', now - fetch_start)
                    if tracer is not None:
                        tracer.add_span('infeed_wait', 'consumer',
                                        fetch_start, now - fetch_start)
                    if goodput is not None:
                        goodput.note_fetch(now - fetch_start, batch)
                    step_start = now
                    yield batch
                    # the time the consumer held the generator suspended IS
                    # its train step (plus any device sync inside it);
                    # the step's end doubles as the next fetch's start
                    fetch_start = time.perf_counter()
                    step_elapsed = fetch_start - step_start
                    if latency is not None:
                        latency.record('train_step', step_elapsed)
                    if tracer is not None:
                        tracer.add_span('train_step', 'consumer', step_start,
                                        step_elapsed)
                    if goodput is not None:
                        goodput.finish_step(step_elapsed)
        except Exception as e:
            self._error = e
            raise
        finally:
            self._in_iter = False

    def _iter_impl(self):
        raise NotImplementedError

    def _cache_hot(self):
        """True when replay epochs are served from an in-memory cache and the
        underlying reader need not be reset."""
        return False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()


class JaxDataLoader(JaxLoaderBase):
    """Yields dicts of numpy column batches of exactly ``batch_size`` rows
    (last partial batch dropped when ``drop_last``, else yielded short).

    Works with both row-granular readers (``make_reader``) and batched readers
    (``make_batch_reader``); batched input is fed column-wise into vectorized
    buffers, never exploded into python rows (the perf trap the reference's
    plain ``DataLoader`` falls into and ``BatchedDataLoader`` fixes,
    ``pytorch.py:204-216`` vs ``:352-408``). NGram readers batch through
    per-timestep collation: a batch is ``{offset: {field: (B, ...) array}}``
    and windows shuffle as whole units (reference ngram batching lives only
    in the TF adapter, ``tf_utils.py:141-183``).

    :param shuffling_queue_capacity: 0 disables shuffling; otherwise a
        uniform-shuffling buffer of that many rows decorrelates row-group order.
    :param transform_fn: optional callable applied to each finished batch dict.
    :param inmemory_cache_all: cache epoch-1 batches and replay them for
        subsequent epochs without touching the reader (reference
        ``pytorch.py:292-321``).
    """

    def __init__(self, reader, batch_size=1, shuffling_queue_capacity=0,
                 transform_fn=None, drop_last=False, seed=None,
                 inmemory_cache_all=False, pad_spec=None, device_decode=True,
                 prefetch_depth=None):
        super(JaxDataLoader, self).__init__(reader)
        # NGram rows are {offset: namedtuple} windows; they batch through
        # per-timestep collation into {offset: dict-of-column-arrays} —
        # mirroring the TF adapter's ngram path (reference
        # ``tf_utils.py:141-183``; the reference torch loader refuses ngram,
        # ``pytorch.py:150-152``).
        self._ngram = getattr(reader, 'ngram', None)
        if self._ngram is not None and pad_spec:
            raise ValueError('pad_spec is not supported with NGram readers '
                             '(window fields are fixed-shape per timestep)')
        self.batch_size = batch_size
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self.transform_fn = transform_fn
        self.drop_last = drop_last
        self.seed = seed
        self.inmemory_cache_all = inmemory_cache_all
        self.pad_spec = validate_pad_spec(pad_spec)
        if self.pad_spec:
            schema_fields = getattr(getattr(reader, 'schema', None), 'fields', None)
            if schema_fields is not None:
                check_pad_spec_fields(self.pad_spec, schema_fields,
                                      'JaxDataLoader')
        self._cache = [] if inmemory_cache_all else None
        self._cache_complete = False
        #: The reader's :class:`~petastorm_tpu.lineage.LineageTracker`. When
        #: lineage is on, every batch rides a packed int64 source column
        #: through the shuffling buffer and finished batches expose
        #: ``batch['_provenance']`` (a
        #: :class:`~petastorm_tpu.lineage.BatchProvenance`). NGram batches
        #: carry no per-row column (windows span source rows); use
        #: ``reader.explain_batch()`` at item granularity there.
        self._lineage = getattr(reader, 'lineage', None)
        self._lineage_on = (self._ngram is None
                            and getattr(self._lineage, 'enabled', False))
        #: The reader pool's ReaderStats (None for readers without one):
        #: the loader gauges shuffle-buffer occupancy into it, and the
        #: device-staging helpers time ``jax.device_put`` against it.
        self.stats = getattr(reader, 'stats', None)
        #: End-to-end batch latency (ventilate → finished batch): recorded
        #: here — the LAST delivery point — via the packed lineage sources,
        #: so the reader's own per-item e2e recording defers to the loader
        #: (one observation per delivered unit, never double-counted).
        self._e2e_on = (self._lineage_on
                        and getattr(self.stats, 'latency', None) is not None)
        if self._e2e_on:
            defer = getattr(reader, '_defer_e2e_to_loader', None)
            if defer is not None:
                defer()
        #: Depth of the :func:`prefetch_to_device` / :func:`prefetch_batches`
        #: window :meth:`iter_prefetched` uses (docs/readahead.md knob note).
        self.prefetch_depth = resolve_prefetch_depth(prefetch_depth)
        # -- device-side decode (docs/decode.md "Device-side decode") ----------
        #: name -> DeviceColumnPlan claimed from a bytes-through reader; the
        #: loader decodes these raw (n, stride) uint8 columns under jax.jit
        #: at batch delivery (fused with any device-flagged TransformSpec).
        #: ``device_decode=False`` leaves the claim to an outer component
        #: (ShardedJaxLoader decodes post-staging on the global arrays).
        self._device_plans = {}
        self._device_transform_spec = None
        self._device_fused_fn = None
        if device_decode:
            claim = getattr(reader, '_defer_device_decode_to_loader', None)
            if claim is not None and getattr(reader, 'device_decode_plans',
                                             None):
                self._device_plans, self._device_transform_spec = claim()

    def _decode_on_device(self, batch):
        """Run the jitted decode (+ fused device ``TransformSpec``) over a
        bytes-through batch's device-compatible columns; host-only values
        merge back untouched."""
        from petastorm_tpu.ops.decode import (build_fused_infeed,
                                              split_device_columns)
        if self._device_fused_fn is None:
            self._device_fused_fn = build_fused_infeed(
                self._device_plans, self._device_transform_spec)
        device_cols, host_cols = split_device_columns(
            batch, self._device_plans,
            include_unplanned=self._device_transform_spec is not None)
        out = dict(self._device_fused_fn(device_cols))
        out.update(host_cols)
        planned = [n for n in self._device_plans if n in device_cols]
        if planned and self.stats is not None:
            rows = int(device_cols[planned[0]].shape[0])
            self.stats.add('rows_decoded_device', rows * len(planned))
        return out

    def _cache_hot(self):
        return self._cache_complete

    # -- buffer construction -------------------------------------------------
    def _make_batched_buffer(self):
        if self.shuffling_queue_capacity > 0:
            min_after = max(1, self.shuffling_queue_capacity - self.batch_size)
            return BatchedRandomShufflingBuffer(
                self.shuffling_queue_capacity + self.batch_size,
                min_after_retrieve=min_after, batch_size=self.batch_size,
                seed=self.seed)
        return BatchedNoopShufflingBuffer(self.batch_size)

    def _iter_impl(self):
        if self._cache_complete:
            for batch in self._cache:
                yield batch
            return
        if self._cache is not None:
            # A prior abandoned iteration may have left partial batches.
            self._cache = []
        if self.reader.batched_output:
            gen = self._iter_batched()
        elif self._ngram is not None:
            if getattr(self.reader, 'ngram_chunked', False):
                gen = self._iter_ngram_chunked()
            else:
                gen = self._iter_ngram()
        else:
            gen = self._iter_rows()
        for batch in gen:
            # the packed source column must never reach user transforms or
            # the model: pop it here, re-attach as the provenance object
            sources = (batch.pop(LINEAGE_COLUMN, None)
                       if self._lineage_on and isinstance(batch, dict)
                       else None)
            if self._device_plans and isinstance(batch, dict):
                # decode raw planned columns (and run the fused device
                # TransformSpec) as ONE jitted program, before any host
                # pad/transform sees the batch
                batch = self._decode_on_device(batch)
            if self.pad_spec:
                batch = pad_ragged_batch(batch, self.pad_spec)
            if self.transform_fn is not None:
                batch = self.transform_fn(batch)
            if sources is not None and isinstance(batch, dict):
                batch[PROVENANCE_KEY] = BatchProvenance(sources, self._lineage)
                if self._e2e_on and len(sources):
                    # ventilate timestamp of the batch's oldest source item
                    # → now: the end-to-end latency of this delivery,
                    # correlated through the lineage seqs the provenance
                    # column already carries. The smallest seq IS the
                    # earliest-registered item (one min, no unique/sort on
                    # the per-batch path).
                    ts = self._lineage.ventilated_ts(
                        int(np.asarray(sources).min()) >> PACK_SHIFT)
                    if ts is not None:
                        self.stats.record_latency(
                            'e2e_batch', time.perf_counter() - ts)
            if self._cache is not None:
                self._cache.append(batch)
            yield batch
        if self._cache is not None:
            self._cache_complete = True

    def _drive_batched_buffer(self, column_stream, post=None):
        """Shared batched-buffer loop: feed column dicts, drain fixed-size
        batches, honor ``drop_last`` on the tail. ``post`` maps each
        retrieved batch (the chunked NGram path unflattens its keys)."""
        post = post or (lambda b: b)
        buffer = self._make_batched_buffer()
        stats = self.stats
        for columns in column_stream:
            while not buffer.can_add():
                yield post(buffer.retrieve())
            buffer.add_many(columns)
            if stats is not None:
                stats.gauge('shuffle_buffer_depth', buffer.size)
            while buffer.can_retrieve() and buffer.size >= self.batch_size:
                yield post(buffer.retrieve())
        buffer.finish()
        while buffer.can_retrieve():
            batch = buffer.retrieve()
            n = len(next(iter(batch.values())))
            if n == self.batch_size or not self.drop_last:
                yield post(batch)

    def _iter_batched(self):
        lineage_on = self._lineage_on
        reader = self.reader

        def columns():
            for chunk in reader:
                cols = sanitize_jax_types(
                    chunk._asdict() if hasattr(chunk, '_asdict') else dict(chunk))
                if lineage_on:
                    seq = reader.last_seq
                    n = len(next(iter(cols.values()))) if cols else 0
                    if seq is not None and n:
                        # one vectorized int64 column per chunk: the rows'
                        # packed source ids survive shuffling/batching
                        cols[LINEAGE_COLUMN] = pack_rows(seq, n)
                yield cols
        return self._drive_batched_buffer(columns())

    def _iter_rows(self):
        # per-ROW hook: read the results reader's plain attributes directly
        # and pack inline — property indirection per row is measurable on
        # small-row-group stores
        results_reader = getattr(self.reader, '_results_reader', None)
        lineage_on = self._lineage_on and results_reader is not None

        def prepare(row):
            d = sanitize_jax_types(row._asdict()
                                   if hasattr(row, '_asdict') else dict(row))
            if lineage_on:
                seq = results_reader.last_seq
                offset = results_reader.last_row_offset
                if seq is not None and offset is not None:
                    d[LINEAGE_COLUMN] = (seq << PACK_SHIFT) | offset
            return d
        return self._iter_row_stream(prepare, self._collate)

    def _iter_ngram_chunked(self):
        """Vectorized NGram batching: whole columnar window chunks
        (``Reader.iter_ngram_chunks``) collate with one fancy-index per
        (offset, field) per chunk and batch through the BATCHED buffers under
        flattened ``(offset, field)`` keys — zero per-window Python, the
        consumer-side twin of the worker's columnar window path. Windows
        still shuffle as whole units: the batched buffer permutes rows (=
        windows) with one permutation across all columns, so timestep
        alignment survives. Yields the same ``{offset: {field: (B, ...)}}``
        layout as :meth:`_iter_ngram`."""
        offsets, base, fields_at = self._ngram.timestep_layout(
            self.reader.schema.fields)

        def take_rows(col, pos):
            # windows over a gap-free row range index consecutive rows:
            # slice the decoded column zero-copy instead of a fancy-index
            # gather (contiguous-slice batch assembly, docs/decode.md)
            if (len(pos) and int(pos[-1]) - int(pos[0]) == len(pos) - 1
                    and bool(np.all(np.diff(pos) == 1))):
                lo = int(pos[0])
                return col[lo:lo + len(pos)]
            return col[pos]

        def collate_chunks():
            for chunk in self.reader.iter_ngram_chunks():
                flat = {}
                for off in offsets:
                    pos = chunk.starts + (off - base)
                    for name in fields_at[off]:
                        col = chunk.columns.get(name)
                        if col is not None:
                            flat[(off, name)] = _sanitize_value(
                                take_rows(col, pos))
                yield flat

        def unflatten(batch):
            out = {}
            for (off, name), col in batch.items():
                out.setdefault(off, {})[name] = col
            return out

        return self._drive_batched_buffer(collate_chunks(), post=unflatten)

    def _iter_ngram(self):
        """NGram windows ({offset: namedtuple}) → per-timestep collated
        batches: ``{offset: {field: (B, ...) array}}`` — windows shuffle as
        whole units so timestep alignment survives the buffer."""
        def collate(windows):
            out = {}
            for offset in sorted(windows[0].keys()):
                rows = [sanitize_jax_types(dict(w[offset]._asdict()))
                        for w in windows]
                out[offset] = self._collate(rows)
            return out
        return self._iter_row_stream(lambda w: w, collate)

    def _iter_row_stream(self, prepare, collate):
        """Shared row-granular loop: shuffle buffer → fixed-size batches."""
        if self.shuffling_queue_capacity > 0:
            min_after = max(1, self.shuffling_queue_capacity - 1)
            buffer = RandomShufflingBuffer(
                self.shuffling_queue_capacity, min_after_retrieve=min_after,
                seed=self.seed)
        else:
            buffer = NoopShufflingBuffer()
        pending = []

        def drain(final):
            rows = pending
            while buffer.can_retrieve():
                rows.append(buffer.retrieve())
                if len(rows) == self.batch_size:
                    yield collate(rows)
                    rows.clear()
            if final and rows and not self.drop_last:
                yield collate(rows)

        stats = self.stats
        row_count = 0
        for row in self.reader:
            row = prepare(row)
            while not buffer.can_add():
                for b in drain(False):
                    yield b
                if not buffer.can_retrieve():
                    break
            buffer.add_many([row])
            # sample the gauge sparsely: a lock acquire per row would tax the
            # very hot path this telemetry exists to diagnose
            row_count += 1
            if stats is not None and row_count % 64 == 1:
                stats.gauge('shuffle_buffer_depth', buffer.size)
            for b in drain(False):
                yield b
        buffer.finish()
        for b in drain(True):
            yield b

    @staticmethod
    def _collate(rows):
        keys = rows[0].keys()
        out = {}
        for k in keys:
            if k == LINEAGE_COLUMN:
                # packed int sources: one fromiter, no per-row asarray
                out[k] = np.fromiter((r[k] for r in rows), dtype=np.int64,
                                     count=len(rows))
                continue
            vals = [np.asarray(r[k]) for r in rows]
            contiguous = _contiguous_rows_view(vals)
            if contiguous is not None:
                # the batch IS a contiguous range of one decoded column:
                # emit the zero-copy slice instead of re-collating rows
                # (docs/decode.md "contiguous-slice batch assembly")
                out[k] = contiguous
                continue
            shapes = {v.shape for v in vals}
            kinds = {v.dtype.kind for v in vals}
            if len(shapes) == 1 and not (kinds & set(_DEVICE_INCOMPATIBLE_KINDS)):
                out[k] = np.stack(vals)
            else:
                # Ragged (shape=(None,...)) or string/object fields cannot form a
                # dense device batch; keep them as a host-side object column.
                col = np.empty(len(vals), dtype=object)
                for i, v in enumerate(vals):
                    col[i] = v
                out[k] = col
        return out


class ShardedJaxLoader(JaxLoaderBase):
    """Wraps a ``JaxDataLoader`` and lifts each host-local numpy batch into a
    **global** ``jax.Array`` sharded over ``mesh`` along ``batch_axis``.

    Under multi-host TPU each process constructs only its local shard
    (``local_batch_size = global_batch_size // process_count``) and XLA sees one
    logical array — the idiomatic replacement for the reference's static
    rank/size shard arithmetic. ``drop_last`` is forced True (no ragged
    batches), and under ``process_count > 1`` every step is preceded by a
    cross-host readiness allgather so all hosts yield exactly the same number
    of steps even when row-group sharding is unbalanced — a host with a
    surplus batch drops it instead of deadlocking the others' collectives
    (SURVEY §7 "hard parts").

    String/object columns cannot live in HBM; they are returned under
    ``batch['_host']`` untouched.

    Bytes-through readers: this loader claims the device-decode plans and
    decodes POST-staging (jitted over the global sharded arrays) — but only
    when no host stage needs the decoded values first. With a
    ``transform_fn`` (or a ``pad_spec`` naming a planned column) the claim
    is declined and the reader host-decodes, so the transform always
    receives decoded numpy columns; fuse device-side work through a
    ``TransformSpec(device=True)`` on the reader instead.

    NGram readers are supported: each step yields the nested
    ``{offset: {field: global jax.Array}}`` layout, every timestep's columns
    sharded over ``batch_axis`` at WINDOW granularity (``local_batch_size``
    windows per process), with the same lockstep-stop protocol.
    """

    def __init__(self, reader, mesh, local_batch_size, batch_axis='data',
                 shuffling_queue_capacity=0, transform_fn=None, seed=None,
                 inmemory_cache_all=False, pad_spec=None, prefetch_depth=None):
        super(ShardedJaxLoader, self).__init__(reader)
        from jax.sharding import NamedSharding, PartitionSpec
        # NGram batches are nested {offset: {field: array}}; each timestep's
        # columns stage into global arrays per offset (window batches shard
        # over the batch axis exactly like row batches)
        self._ngram = getattr(reader, 'ngram', None)
        self.mesh = mesh
        self.batch_axis = batch_axis
        normalized_pad_spec = validate_pad_spec(pad_spec)
        require_single_bucket_pad_spec(normalized_pad_spec,
                                       'ShardedJaxLoader')
        # device_decode=False: the inner loader must NOT decode the raw
        # bytes-through columns pre-staging — this loader claims them below
        # and decodes post-staging, jitted over the GLOBAL sharded arrays,
        # so decode work shards along the batch axis with the data
        self._loader = JaxDataLoader(
            reader, batch_size=local_batch_size,
            shuffling_queue_capacity=shuffling_queue_capacity,
            transform_fn=transform_fn, drop_last=True, seed=seed,
            inmemory_cache_all=inmemory_cache_all, pad_spec=pad_spec,
            device_decode=False, prefetch_depth=prefetch_depth)
        self._pspec = PartitionSpec(batch_axis)
        self._named_sharding = NamedSharding(mesh, self._pspec)
        self.stats = self._loader.stats
        self.prefetch_depth = self._loader.prefetch_depth
        if self.goodput is not None:
            # This loader drives the inner loader's _iter_impl directly,
            # bypassing its instrumented __iter__ — the OUTER monitor is the
            # live one. Share it (the staging sites below feed it) and
            # re-register it over the inner loader's dormant registration.
            self._loader.goodput = self.goodput
            register = getattr(reader, 'register_goodput', None)
            if register is not None:
                register(self.goodput)
        # -- device-side decode (docs/decode.md "Device-side decode") ----------
        # This loader decodes POST-staging (jitted over the global sharded
        # arrays), so the inner loader's pad/transform stages would see the
        # raw (n, stride) uint8 grids. A host transform_fn (or a pad_spec
        # over a planned column) needs decoded host values BEFORE staging —
        # in that case decline the claim and let the reader host-decode,
        # keeping the transform's decoded-numpy contract (a device=True
        # TransformSpec still fuses into the jitted decode).
        self._device_plans = {}
        self._device_fused_fn = None
        claim = getattr(reader, '_defer_device_decode_to_loader', None)
        available_plans = getattr(reader, 'device_decode_plans', None)
        if claim is not None and available_plans:
            padded_planned = sorted(set(normalized_pad_spec or {})
                                    & set(available_plans))
            if transform_fn is not None:
                logger.info(
                    'ShardedJaxLoader: transform_fn needs decoded host '
                    'columns; declining the bytes-through claim (the reader '
                    'host-decodes). Use a TransformSpec(device=True) on the '
                    'reader to keep decode on the accelerator.')
            elif padded_planned:
                logger.info(
                    'ShardedJaxLoader: pad_spec names device-planned '
                    'columns %s which pad before staging; declining the '
                    'bytes-through claim (the reader host-decodes).',
                    padded_planned)
            else:
                plans, device_spec = claim()
                if plans:
                    from petastorm_tpu.ops.decode import build_fused_infeed
                    self._device_plans = plans
                    self._device_fused_fn = build_fused_infeed(plans,
                                                               device_spec)

    def _cache_hot(self):
        return self._loader._cache_hot()

    def _iter_impl(self):
        import jax
        lockstep = jax.process_count() > 1
        it = self._loader._iter_impl()
        while True:
            batch = next(it, None)
            if lockstep:
                # Cross-host agreement before every step: row-group sharding
                # can hand one host a batch more than another (9 row groups
                # over 2 hosts), and a host entering a collective the others
                # never reach deadlocks the cluster. All hosts stop together
                # at the shortest host's stream; a surplus local batch is
                # dropped (the multi-host extension of drop_last).
                if not _all_processes_ready(batch is not None):
                    # Drain the surplus before stopping: abandoning the
                    # stream mid-epoch would leave the Reader unfinished
                    # (reset() would refuse), breaking the NEXT pass on this
                    # host only. With the epoch cache on, the inner generator
                    # must run to completion (the cache replays these
                    # batches); otherwise discard raw pool results without
                    # decoding/collating them (heavily unbalanced shards
                    # would pay full window/batch assembly for data nobody
                    # reads).
                    drain = getattr(self.reader, 'drain', None)
                    if self._loader.inmemory_cache_all or drain is None:
                        for _ in it:
                            pass
                    else:
                        drain()
                    return
            elif batch is None:
                return
            stats = self._loader.stats
            tracer = self.tracer
            goodput = self.goodput
            if self._ngram is not None:
                yield {off: stage_to_global(cols, self._named_sharding,
                                            stats=stats, tracer=tracer,
                                            goodput=goodput)
                       for off, cols in batch.items()}
            else:
                if self._device_plans and stats is not None:
                    planned = [n for n in self._device_plans if n in batch]
                    if planned:
                        stats.add('rows_decoded_device',
                                  int(batch[planned[0]].shape[0])
                                  * len(planned))
                yield stage_to_global(batch, self._named_sharding, stats=stats,
                                      tracer=tracer,
                                      fused_fn=self._device_fused_fn,
                                      goodput=goodput)


def _all_processes_ready(local_ready: bool) -> bool:
    """True iff EVERY process has a next batch. One tiny allgather per step —
    the price of streaming readers not knowing their row count up front."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([1 if local_ready else 0], np.int32))
    return bool(np.asarray(flags).min())


def stage_to_global(batch, named_sharding, stats=None, tracer=None,
                    fused_fn=None, goodput=None):
    """Assemble a host batch dict into global ``jax.Array``s over
    ``named_sharding``; device-incompatible (string/object) columns ride
    under ``batch['_host']`` untouched — the single definition of the
    'what can live in HBM' split. ``stats`` (a ``ReaderStats``) accumulates
    the assembly wall time as ``device_stage_s``; ``tracer`` (a
    :class:`~petastorm_tpu.tracing.Tracer`) records it as a ``device_stage``
    span. ``fused_fn`` (an ``ops.decode.build_fused_infeed`` program) runs
    over the assembled device dict — bytes-through decode plus any device
    ``TransformSpec``, jitted over the GLOBAL sharded arrays so the work
    shards along the batch axis with the data. ``goodput`` (a
    :class:`~petastorm_tpu.goodput.GoodputMonitor`) attributes the same
    wall time to the current step's ``h2d_stage`` leg."""
    import jax
    timed = stats is not None or tracer is not None or goodput is not None
    start = time.perf_counter() if timed else 0.0
    device, host = {}, {}
    for name, value in batch.items():
        if name == PROVENANCE_KEY:
            # under '_host' with the other non-HBM values: every top-level
            # entry except '_host' stays a jax.Array, so a staged batch can
            # still be passed whole into jit
            host[name] = value
        elif _is_device_compatible(value):
            device[name] = jax.make_array_from_process_local_data(
                named_sharding, value)
        else:
            host[name] = value
    if fused_fn is not None and device:
        device = dict(fused_fn(device))
    if host:
        device['_host'] = host
    if timed:
        elapsed = time.perf_counter() - start
        if stats is not None:
            stats.add_time('device_stage_s', elapsed)
            stats.record_latency('device_stage', elapsed)
        if tracer is not None:
            tracer.add_span('device_stage', 'device', start, elapsed)
        if goodput is not None:
            goodput.note_stage(elapsed)
    return device


def infeed_diagnosis(snapshot: dict, heartbeats=None,
                     stall_after_s=None, roofline=None, latency=None,
                     slo=None) -> dict:
    """Classify an infeed pipeline from a ``ReaderStats`` snapshot
    (``reader.diagnostics`` / ``loader.stats.snapshot()``) and recommend the
    knobs that attack its bottleneck.

    The signatures (see ``docs/troubleshooting.md``):

    - **io-bound** — storage stall dominates decode: raise ``io_readahead``
      (overlap reads with decode) before raising ``workers_count``.
    - **decode-bound** — decode dominates and reads are already hidden:
      ``io_readahead`` cannot help; raise ``workers_count`` / move decode
      work (decode_hints, transforms) instead.
    - **consumer-bound** — workers outrun the consumer (large
      ``worker_publish_wait_s``): the training step, not the reader, is the
      ceiling.

    ``heartbeats`` (``reader.health.heartbeats()``) optionally folds the
    live health layer into the verdict: the returned dict gains
    ``pipeline_state`` (healthy/degraded/stalled/starving) and
    ``stalled_entities``, and a stalled entity overrides ``bottleneck`` with
    ``'stalled'`` — the same :func:`petastorm_tpu.health.classify_pipeline`
    call the watchdog and ``/healthz`` make, so the CLI's ``-d`` output and
    the debug endpoint can never disagree. ``stall_after_s`` defaults to
    :data:`petastorm_tpu.health.DEFAULT_STALL_AFTER_S`.

    ``roofline`` (a :meth:`~petastorm_tpu.reader.Reader.profile` result or
    its :func:`~petastorm_tpu.profiler.roofline_summary`) adds a
    ``roofline`` section — measured samples/s as a fraction of the
    calibrated binding-stage ceiling — so the diagnosis says not only
    *which* stage binds but *how far from the host's measured limit* the
    pipeline runs (see ``docs/profiling.md``).

    ``latency`` (a :class:`~petastorm_tpu.latency.PipelineLatency`, e.g.
    ``reader.stats.latency``) adds a ``latency`` section of per-stage
    percentile summaries; the snapshot's derived ``queue_wait_p50_s`` /
    ``queue_wait_p99_s`` / ``e2e_latency_p99_s`` keys are surfaced either
    way. ``slo`` (an :class:`~petastorm_tpu.latency.SLOMonitor` verdict)
    embeds the SLO burn accounting (see ``docs/latency.md``).
    """
    from petastorm_tpu.health import (DEFAULT_STALL_AFTER_S,
                                      bottleneck_signals, classify_pipeline)
    from petastorm_tpu.workers.stats import (batched_decode_fraction,
                                             device_decode_fraction,
                                             readahead_hit_rate,
                                             recommend_io_readahead)
    signals = bottleneck_signals(snapshot)
    io_s, decode_s = signals['io_s'], signals['decode_s']
    out = {
        'bottleneck': signals['bottleneck'],
        'io_s': round(io_s, 4),
        'decode_s': round(decode_s, 4),
        'io_decode_ratio': round(io_s / decode_s, 3) if decode_s else None,
        'io_overlap_fraction': snapshot.get('io_overlap_fraction', 0.0),
        'readahead_hit_rate': readahead_hit_rate(snapshot),
        'recommended_io_readahead': recommend_io_readahead(snapshot),
        'rows_quarantined': snapshot.get('rows_quarantined', 0),
        'rows_decoded_batched': snapshot.get('rows_decoded_batched', 0),
        'rows_decoded_percell': snapshot.get('rows_decoded_percell', 0),
        'batched_decode_fraction': batched_decode_fraction(snapshot),
        # ONE device-side block: decode placement, per-step goodput, and the
        # prefetch ring together answer "is the accelerator actually fed?"
        # without hunting across sections (docs/goodput.md).
        'device': {
            'rows_decoded_device': snapshot.get('rows_decoded_device', 0),
            'bytes_shipped_raw': snapshot.get('bytes_shipped_raw', 0),
            'device_decode_fraction': device_decode_fraction(snapshot),
            'goodput_fraction': snapshot.get('goodput_fraction'),
            'data_stall_fraction': snapshot.get('data_stall_fraction'),
            'prefetch_occupancy': snapshot.get('prefetch_occupancy', 0),
            'prefetch_occupancy_max': snapshot.get('prefetch_occupancy_max',
                                                   0),
        },
        'queue_wait_p50_s': round(snapshot.get('queue_wait_p50_s', 0.0), 6),
        'queue_wait_p99_s': round(snapshot.get('queue_wait_p99_s', 0.0), 6),
        'e2e_latency_p99_s': round(snapshot.get('e2e_latency_p99_s', 0.0), 6),
        'hint': signals['hint'],
    }
    if signals.get('tail_stall'):
        out['tail_stall'] = True
    if latency is not None:
        out['latency'] = latency.summary()
    if slo is not None:
        out['slo'] = slo
    if heartbeats is not None:
        verdict = classify_pipeline(
            heartbeats, snapshot,
            DEFAULT_STALL_AFTER_S if stall_after_s is None else stall_after_s)
        out['pipeline_state'] = verdict['state']
        out['stalled_entities'] = verdict['stalled_entities']
        if verdict['state'] == 'stalled':
            # a wedged entity trumps any aggregate signal: time sums stop
            # moving the moment the stall starts, so the ratios describe the
            # past, not the problem
            out['bottleneck'] = 'stalled'
            out['hint'] = verdict['hint']
    if roofline is not None:
        from petastorm_tpu.profiler import roofline_summary
        out['roofline'] = (roofline_summary(roofline)
                           if roofline.get('kind') ==
                           'petastorm_tpu_roofline_profile' else roofline)
    return out


def make_jax_loader(reader, batch_size=1, mesh=None, batch_axis='data',
                    shuffling_queue_capacity=0, transform_fn=None,
                    drop_last=False, seed=None, inmemory_cache_all=False,
                    pad_spec=None, device_decode=True, prefetch_depth=None):
    """Factory: plain host loader when ``mesh is None``, else a sharded loader.

    With a mesh, ``batch_size`` is the **per-process** batch size; the global
    logical batch is ``batch_size * jax.process_count()``.

    ``device_decode=False`` opts the host loader out of claiming a
    bytes-through reader's raw columns (the reader then host-decodes them,
    keeping its yield contract). ``prefetch_depth`` sets the loaders'
    :meth:`~JaxLoaderBase.iter_prefetched` lookahead window (default: the
    ``PETASTORM_TPU_PREFETCH_DEPTH`` env var, else 2 — docs/readahead.md).
    """
    if mesh is None:
        return JaxDataLoader(reader, batch_size=batch_size,
                             shuffling_queue_capacity=shuffling_queue_capacity,
                             transform_fn=transform_fn, drop_last=drop_last,
                             seed=seed, inmemory_cache_all=inmemory_cache_all,
                             pad_spec=pad_spec, device_decode=device_decode,
                             prefetch_depth=prefetch_depth)
    return ShardedJaxLoader(reader, mesh, batch_size, batch_axis=batch_axis,
                            shuffling_queue_capacity=shuffling_queue_capacity,
                            transform_fn=transform_fn, seed=seed,
                            inmemory_cache_all=inmemory_cache_all,
                            pad_spec=pad_spec, prefetch_depth=prefetch_depth)


def epoch_cache_on_device(loader, sharding=None):
    """Iterate epochs forever, caching epoch 1 **on device**.

    Epoch 1 stages each batch into HBM (``jax.device_put``) and keeps the
    device arrays; epochs 2+ replay them with zero host work and zero
    transfers — infeed disappears entirely for datasets that fit in device
    memory (the device-side upgrade of the reference's host-side
    ``inmemory_cache_all``, ``pytorch.py:292-321``). Host-only columns
    (``_host`` or string/object arrays) are kept on host, untouched.

    :param loader: an iterable yielding batch dicts; re-iterated never (the
        cached epoch is replayed instead).
    :param sharding: optional ``jax.sharding.Sharding`` for the device copies.
    """
    import jax

    def stage(batch):
        def put(x):
            if not _is_device_compatible(x):
                return x
            return jax.device_put(x, sharding) if sharding is not None \
                else jax.device_put(x)
        return jax.tree_util.tree_map(put, batch)

    cache = []
    for batch in loader:
        staged = stage(batch)
        cache.append(staged)
        yield staged
    if not cache:
        return
    while True:
        for batch in cache:
            yield batch


def prefetch_batches(iterator, size=None, health=None, stats=None):
    """Host-side lookahead WITHOUT device staging: a background thread keeps
    up to ``size`` numpy batches ready; the jitted step's own call performs
    the host→device transfer. ``health`` (a
    :class:`~petastorm_tpu.health.HealthMonitor`, e.g. ``reader.health``)
    lets the prefetch thread publish liveness heartbeats.

    When to use which prefetcher: :func:`prefetch_to_device` issues an
    explicit ``jax.device_put`` per batch, overlapping the H2D DMA with
    compute — right for large batches where transfer bandwidth matters. For
    small/latency-bound batches the extra per-batch transfer dispatch (and
    its GIL traffic against the decode workers) costs more than it hides:
    passing numpy straight into ``jit`` folds transfer+execute into one
    dispatch. Measured on a v5e LM bench (64×257 int32 batches, ~1ms steps):
    86-90% infeed overlap via ``prefetch_to_device`` vs ~99% via
    ``prefetch_batches``. ``stats`` (a ``ReaderStats``) gauges the live ring
    depth as ``prefetch_occupancy`` — an empty ring at step boundaries is
    the classic starving signal."""
    return _pipeline(iterator, resolve_prefetch_depth(size),
                     lambda batch: batch, health=health, stats=stats)


def prefetch_to_device(iterator, size=None, sharding=None, stats=None,
                       tracer=None, health=None, fused_fn=None, goodput=None):
    """Double-buffered host→device prefetch.

    Stages up to ``size`` batches ahead of the consumer on a background thread
    so the ``jax.device_put`` (host→HBM DMA) of batch N+1 overlaps the compute
    of batch N. When batches are already global ``jax.Array``s (from
    ``ShardedJaxLoader``) the transfer has been issued at construction time and
    this just provides pipelining depth. See :func:`prefetch_batches` for the
    small-batch/latency-bound alternative.

    :param sharding: optional ``jax.sharding.Sharding`` applied via
        ``jax.device_put`` to plain numpy batches.
    :param stats: optional ``ReaderStats`` (e.g. ``reader.stats`` /
        ``loader.stats``) accumulating the transfer-dispatch wall time as
        ``device_stage_s``.
    :param tracer: optional ``Tracer`` (e.g. ``reader.tracer``) recording
        each transfer dispatch as a ``device_stage`` span — the prefetch
        thread gets its own track, so the overlap with the consumer's
        ``train_step`` spans is visible directly.
    :param health: optional :class:`~petastorm_tpu.health.HealthMonitor`
        (e.g. ``reader.health`` / ``loader.health``); the prefetch thread
        publishes a ``loader-prefetch`` heartbeat entity so the watchdog can
        tell a wedged device transfer from a starving reader.
    :param fused_fn: optional ``ops.decode.build_fused_infeed`` program run
        over each staged batch's device-compatible columns on the prefetch
        thread — bytes-through decode (+ device ``TransformSpec``) overlaps
        the consumer's compute exactly like the transfer it rides with.
    :param goodput: optional :class:`~petastorm_tpu.goodput.GoodputMonitor`
        (e.g. ``loader.goodput``); each transfer dispatch's wall time is
        attributed to the in-flight step's ``h2d_stage`` leg (thread-safe —
        the put runs on the prefetch thread).
    :param size: lookahead depth; ``None`` resolves the loader knob chain
        (``PETASTORM_TPU_PREFETCH_DEPTH``, else 2 — docs/readahead.md).
    """
    import jax
    size = resolve_prefetch_depth(size)

    def put(batch):
        # _is_device_compatible reads dtype via getattr: global jax.Arrays must
        # NOT be round-tripped through np.asarray (device->host copy; crashes
        # on non-fully-addressable multi-host arrays).
        timed = stats is not None or tracer is not None or goodput is not None
        start = time.perf_counter() if timed else 0.0
        if sharding is None:
            staged = jax.tree_util.tree_map(
                lambda x: jax.device_put(x) if _is_device_compatible(x) else x,
                batch)
        else:
            staged = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sharding) if _is_device_compatible(x) else x,
                batch)
        if fused_fn is not None and isinstance(staged, dict):
            host = {k: v for k, v in staged.items()
                    if not _is_device_compatible(v)}
            dev = {k: v for k, v in staged.items()
                   if _is_device_compatible(v)}
            if dev:
                staged = dict(fused_fn(dev))
                staged.update(host)
        if timed:
            elapsed = time.perf_counter() - start
            if stats is not None:
                stats.add_time('device_stage_s', elapsed)
                stats.record_latency('device_stage', elapsed)
            if tracer is not None:
                tracer.add_span('device_stage', 'device', start, elapsed)
            if goodput is not None:
                goodput.note_stage(elapsed)
        return staged

    return _pipeline(iterator, size, put, health=health, stats=stats)


def _pipeline(iterator, size, put, health=None, stats=None):
    """Shared producer-thread pipeline behind the two prefetchers.
    ``stats`` gauges the ring's live depth as ``prefetch_occupancy`` on
    every enqueue/dequeue — the depth is read under the ring's condition
    but the gauge is recorded OUTSIDE it (the stats lock must never nest
    inside the ring lock)."""
    queue = collections.deque()
    done = object()
    cv = threading.Condition()
    state = {'error': None, 'finished': False}
    beat = health.beat if health is not None else None
    gauge = stats.gauge if stats is not None else None

    def producer():
        try:
            for batch in iterator:
                if state['finished']:   # consumer closed early: stop reading
                    return
                if beat is not None:
                    beat('loader-prefetch', 'staging')
                staged = put(batch)
                with cv:
                    if beat is not None and len(queue) >= size:
                        # blocked on a full prefetch queue = the consumer is
                        # the slow side; idle-class, never a prefetch stall
                        beat('loader-prefetch', 'backpressured')
                    while len(queue) >= size and not state['finished']:
                        cv.wait()
                    if state['finished']:
                        return
                    queue.append(staged)
                    depth = len(queue)
                    cv.notify_all()
                if gauge is not None:
                    gauge('prefetch_occupancy', depth)
                if beat is not None:
                    beat('loader-prefetch', 'idle')
        except Exception as e:  # propagate into the consumer
            state['error'] = e
        finally:
            if beat is not None:
                beat('loader-prefetch', 'done')
            with cv:
                queue.append(done)
                cv.notify_all()

    thread = threading.Thread(target=producer, daemon=True,
                              name='petastorm-tpu-prefetch')
    thread.start()
    try:
        while True:
            with cv:
                while not queue:
                    cv.wait()
                item = queue.popleft()
                # the done sentinel is not a buffered batch: the gauge must
                # read 0 once the ring is drained, not count the marker
                depth = len(queue) - (1 if queue and queue[-1] is done else 0)
                cv.notify_all()
            if item is done:
                if state['error'] is not None:
                    raise state['error']
                return
            if gauge is not None:
                gauge('prefetch_occupancy', depth)
            yield item
    finally:
        with cv:
            state['finished'] = True
            queue.clear()
            cv.notify_all()
