"""Ring attention: exact attention over sequences sharded across devices.

Each device holds one sequence chunk of q/k/v. K/V chunks rotate around the
mesh axis with ``jax.lax.ppermute`` (XLA lowers this to ICI neighbor sends)
while every device folds each visiting chunk into its online-softmax
accumulators — compute on chunk j overlaps the transfer of chunk j+1, so the
ring latency hides behind the attention FLOPs. Memory per device stays
O(L_local²-free): only (o, m, l) accumulators and one in-flight kv chunk.

This is the long-context/sequence-parallel capability the data-side NGram
assembler (``petastorm_tpu/ngram.py``) feeds; model-side it composes with data
and tensor parallelism over the same mesh (axes 'data'/'seq'/'model').

Use inside ``jax.shard_map`` with q/k/v partitioned over ``axis_name`` on the
sequence dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.attention import attention_block_step, finalize_attention


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True):
    """Exact (optionally causal) attention over a ring-sharded sequence.

    Args:
        q, k, v: local chunks ``(..., L_local, D)``; the global sequence is the
            concatenation of chunks in mesh-axis order.
        axis_name: mesh axis the sequence is sharded over.
        causal: mask by *global* token positions.

    Returns the local output chunk ``(..., L_local, D)`` in q's dtype.
    """
    orig_dtype = q.dtype
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    l_local = q.shape[-2]

    q_pos = my_idx * l_local + jnp.arange(l_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(k_cur, v_cur, o, m, l, ring_step):
        src_idx = (my_idx - ring_step) % n       # whose chunk we hold this step
        k_pos = src_idx * l_local + jnp.arange(l_local)
        return attention_block_step(
            q32, k_cur, v_cur, o, m, l,
            q_positions=q_pos, k_positions=k_pos, causal=causal)

    def step(carry, ring_step):
        k_cur, v_cur, o, m, l = carry
        o, m, l = attend(k_cur, v_cur, o, m, l, ring_step)
        # Rotate kv to the next device; XLA overlaps this with the next
        # iteration's compute when possible.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o, m, l), None

    # Derive accumulators from q32 so they carry the same shard_map
    # varying-axes type as the rotating kv chunks (scan carry typing).
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], -1e30)
    l0 = jnp.zeros_like(q32[..., 0])
    # n-1 rotate-and-attend steps, then fold the last visiting chunk without
    # rotating it onward (the n-th ppermute's output is never read).
    (k_fin, v_fin, o, m, l), _ = jax.lax.scan(
        step, (k32, v32, o0, m0, l0), jnp.arange(n - 1))
    o, m, l = attend(k_fin, v_fin, o, m, l, n - 1)
    return finalize_attention(o, l).astype(orig_dtype)


def make_ring_attention(mesh, seq_axis: str = 'seq', causal: bool = True):
    """Wrap :func:`ring_attention` in a ``shard_map`` over ``mesh``.

    Returns ``fn(q, k, v) -> out`` for global arrays of shape
    ``(batch, heads, L, D)`` with L sharded over ``seq_axis`` and batch over
    'data' when present in the mesh.
    """
    from jax.sharding import PartitionSpec as P

    batch_axis = 'data' if 'data' in mesh.axis_names else None
    spec = P(batch_axis, None, seq_axis, None)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=causal)

    return fn
