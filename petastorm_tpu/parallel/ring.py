"""Ring attention: exact attention over sequences sharded across devices.

Each device holds one sequence chunk of q/k/v. K/V chunks rotate around the
mesh axis with ``jax.lax.ppermute`` (XLA lowers this to ICI neighbor sends)
while every device folds each visiting chunk into its online-softmax
accumulators — compute on chunk j overlaps the transfer of chunk j+1, so the
ring latency hides behind the attention FLOPs. Memory per device stays
O(L_local²-free): only (o, m, l) accumulators and one in-flight kv chunk.

This is the long-context/sequence-parallel capability the data-side NGram
assembler (``petastorm_tpu/ngram.py``) feeds; model-side it composes with data
and tensor parallelism over the same mesh (axes 'data'/'seq'/'model').

Use inside ``jax.shard_map`` with q/k/v partitioned over ``axis_name`` on the
sequence dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.attention import (
    _NEG_INF, _FlashDims, _flash_backward_from_prepared,
    _prepare_flash_bwd_q_side, attention_block_step, finalize_attention,
    flash_attention_with_lse, merge_attention_chunks)


def resolve_ring_impl(impl, mesh=None) -> str:
    """Resolve the per-chunk compute implementation. An explicit ``impl``
    wins; otherwise pick 'pallas' exactly when the devices that will run the
    shard_map are TPUs — the MESH decides, not ``jax.default_backend()``
    (a CPU mesh on a TPU-attached host must get the jnp path)."""
    if impl is not None:
        return impl
    if mesh is not None:
        platform = next(iter(mesh.devices.flat)).platform
    else:
        platform = jax.default_backend()
    return 'pallas' if platform == 'tpu' else 'jnp'


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True,
                   impl: str = None, block_q: int = 256, block_k: int = 512):
    """Exact (optionally causal) attention over a ring-sharded sequence.

    Args:
        q, k, v: local chunks ``(..., L_local, D)``; the global sequence is the
            concatenation of chunks in mesh-axis order.
        axis_name: mesh axis the sequence is sharded over.
        causal: mask by *global* token positions.
        impl: per-chunk compute — 'pallas' runs every visiting chunk through
            the fused flash kernels (forward AND backward, via a ring-aware
            custom_vjp), 'jnp' the blockwise online-softmax update (any
            backend, plain autodiff), 'interpret' the Pallas interpreter
            (CI on CPU). Default (None): by ``jax.default_backend()`` —
            callers that know the mesh should resolve via
            :func:`resolve_ring_impl` instead (``make_ring_attention`` does),
            so CPU meshes on TPU-attached hosts get the jnp path.
        block_q, block_k: kernel block sizes for the Pallas path.

    Returns the local output chunk ``(..., L_local, D)`` in q's dtype.
    """
    impl = resolve_ring_impl(impl)
    if impl in ('pallas', 'interpret'):
        # GQA (kv with fewer heads) flows through natively: the per-chunk
        # flash kernels read shared kv via the head map, and fewer kv heads
        # also shrink the rotating ppermute payload.
        return _ring_flash(q, k, v, axis_name, causal, block_q, block_k,
                           impl == 'interpret')
    if impl != 'jnp':
        raise ValueError("impl must be 'pallas', 'jnp' or 'interpret', "
                         "got %r" % (impl,))
    if q.shape[:-2] != k.shape[:-2]:
        # the jnp block update needs matching head counts; repeat kv here so
        # both impls accept the same GQA inputs (the Pallas path stays the
        # memory-efficient one). _FlashDims validates the head ratio with
        # the same error the Pallas path raises.
        _FlashDims(q.shape, k.shape, block_q, block_k)
        group = q.shape[-3] // k.shape[-3]
        k = jnp.repeat(k, group, axis=-3)
        v = jnp.repeat(v, group, axis=-3)
    orig_dtype = q.dtype
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    l_local = q.shape[-2]

    q_pos = my_idx * l_local + jnp.arange(l_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(k_cur, v_cur, o, m, l, ring_step):
        src_idx = (my_idx - ring_step) % n       # whose chunk we hold this step
        k_pos = src_idx * l_local + jnp.arange(l_local)
        return attention_block_step(
            q32, k_cur, v_cur, o, m, l,
            q_positions=q_pos, k_positions=k_pos, causal=causal)

    def step(carry, ring_step):
        k_cur, v_cur, o, m, l = carry
        o, m, l = attend(k_cur, v_cur, o, m, l, ring_step)
        # Rotate kv to the next device; XLA overlaps this with the next
        # iteration's compute when possible.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o, m, l), None

    # Derive accumulators from q32 so they carry the same shard_map
    # varying-axes type as the rotating kv chunks (scan carry typing).
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], -1e30)
    l0 = jnp.zeros_like(q32[..., 0])
    # n-1 rotate-and-attend steps, then fold the last visiting chunk without
    # rotating it onward (the n-th ppermute's output is never read).
    (k_fin, v_fin, o, m, l), _ = jax.lax.scan(
        step, (k32, v32, o0, m0, l0), jnp.arange(n - 1))
    o, m, l = attend(k_fin, v_fin, o, m, l, n - 1)
    return finalize_attention(o, l).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Pallas-kernel ring: per-chunk flash forward/backward + logsumexp merge
# ---------------------------------------------------------------------------

def _chunk_case(src_idx, my_idx):
    """0 = fully visible (src strictly before my queries), 1 = diagonal
    (local causal mask), 2 = fully masked (src strictly after)."""
    return jnp.where(src_idx == my_idx, 1,
                     jnp.where(src_idx < my_idx, 0, 2))


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k,
                         interpret):
    """Forward ring with per-chunk flash kernels. Returns ``(o, lse)`` — o in
    q's dtype, lse float32 ``(..., L_local)`` = the GLOBAL per-row logsumexp
    (saved as the backward's residual).

    Chunks are globally position-aligned, so causality degenerates to three
    whole-chunk cases (``_chunk_case``); the diagonal chunk runs the causal
    kernel, earlier chunks the non-causal one, later chunks are skipped."""
    orig_dtype = q.dtype
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def chunk_summary(k_cur, v_cur, ring_step):
        def full(_):
            return flash_attention_with_lse(
                q, k_cur, v_cur, causal=False, block_q=block_q,
                block_k=block_k, interpret=interpret)

        def diag(_):
            return flash_attention_with_lse(
                q, k_cur, v_cur, causal=True, block_q=block_q,
                block_k=block_k, interpret=interpret)

        def none(_):
            # derive from the operands so the outputs carry the same
            # varying-mesh-axes type as the kernel branches (shard_map vma)
            return (q * jnp.zeros((), q.dtype),
                    q[..., 0].astype(jnp.float32) * 0.0 + _NEG_INF)

        if not causal:
            return full(None)
        src_idx = (my_idx - ring_step) % n
        return jax.lax.switch(_chunk_case(src_idx, my_idx),
                              [full, diag, none], None)

    def step(carry, ring_step):
        k_cur, v_cur, o_acc, m, l = carry
        o_i, lse_i = chunk_summary(k_cur, v_cur, ring_step)
        o_acc, m, l = merge_attention_chunks(o_acc, m, l, o_i, lse_i)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o_acc, m, l), None

    # Derive accumulators from q so they carry the same shard_map
    # varying-axes type as the rotating kv chunks (scan carry typing).
    qz = q.astype(jnp.float32) * 0.0
    o0 = qz
    m0 = qz[..., 0] + _NEG_INF
    l0 = qz[..., 0]
    (k_fin, v_fin, o_acc, m, l), _ = jax.lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(n - 1))
    o_i, lse_i = chunk_summary(k_fin, v_fin, n - 1)
    o_acc, m, l = merge_attention_chunks(o_acc, m, l, o_i, lse_i)
    o = finalize_attention(o_acc, l).astype(orig_dtype)
    lse = jnp.where(l > 0.0, m + jnp.log(jnp.where(l > 0.0, l, 1.0)),
                    _NEG_INF)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k, interpret):
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k,
                                interpret)
    return o


def _ring_flash_fwd(q, k, v, axis_name, causal, block_q, block_k, interpret):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                  block_k, interpret)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, block_q, block_k, interpret, res, do):
    """Ring backward: kv chunks rotate a FULL cycle together with their
    gradient accumulators, so each (dk, dv) collects every device's
    contribution and arrives back at its owner after n steps. dq accumulates
    locally. Per chunk pair, the fused backward kernels recompute p from the
    global lse residual — already the global softmax probabilities, so
    contributions just sum."""
    q, k, v, o, lse = res
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # accumulator init derived from the operands (shard_map vma typing)
    zeros_q = q.astype(jnp.float32) * 0.0
    zeros_kv = k.astype(jnp.float32) * 0.0
    # q-side operands (padded q/do, lse/Δ columns) are step-invariant:
    # prepared once here, only the kv chunk varies inside the scan.
    dims = _FlashDims(q.shape, k.shape, block_q, block_k)
    prep = _prepare_flash_bwd_q_side(dims, q, o, lse, do)

    def pair_grads(k_cur, v_cur, ring_step):
        def full(_):
            return _flash_backward_from_prepared(
                dims, prep, k_cur, v_cur, causal=False, interpret=interpret)

        def diag(_):
            return _flash_backward_from_prepared(
                dims, prep, k_cur, v_cur, causal=True, interpret=interpret)

        def none(_):
            # zeros derived from the operands: same vma type as the kernels
            return (q * jnp.zeros((), q.dtype),
                    k_cur * jnp.zeros((), k_cur.dtype),
                    v_cur * jnp.zeros((), v_cur.dtype))

        if not causal:
            return full(None)
        src_idx = (my_idx - ring_step) % n
        return jax.lax.switch(_chunk_case(src_idx, my_idx),
                              [full, diag, none], None)

    def step(carry, ring_step):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        dq_p, dk_p, dv_p = pair_grads(k_cur, v_cur, ring_step)
        dq_acc = dq_acc + dq_p.astype(jnp.float32)
        dk_cur = dk_cur + dk_p.astype(jnp.float32)
        dv_cur = dv_cur + dv_p.astype(jnp.float32)
        # Rotate the chunk AND its gradient accumulator onward; after n
        # process+rotate steps both are back home.
        rotated = [jax.lax.ppermute(x, axis_name, perm)
                   for x in (k_cur, v_cur, dk_cur, dv_cur)]
        return tuple(rotated) + (dq_acc,), None

    (k_fin, v_fin, dk, dv, dq), _ = jax.lax.scan(
        step, (k, v, zeros_kv, zeros_kv, zeros_q), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def make_ring_attention(mesh, seq_axis: str = 'seq', causal: bool = True,
                        impl: str = None):
    """Wrap :func:`ring_attention` in a ``shard_map`` over ``mesh``.

    Returns ``fn(q, k, v) -> out`` for global arrays of shape
    ``(batch, heads, L, D)`` with L sharded over ``seq_axis`` and batch over
    'data' when present in the mesh. ``impl`` as in :func:`ring_attention`.
    """
    from jax.sharding import PartitionSpec as P

    batch_axis = 'data' if 'data' in mesh.axis_names else None
    spec = P(batch_axis, None, seq_axis, None)
    impl = resolve_ring_impl(impl, mesh)

    from petastorm_tpu.parallel.mesh import shard_map_fn

    @functools.partial(shard_map_fn(), mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=causal, impl=impl)

    return fn
