"""Distributed/parallel primitives for TPU meshes.

The reference's only multi-node story is static shard arithmetic + Horovod env
vars (``spark_dataset_converter.py:122-159``); here the distributed layer is
first-class: mesh construction, partition specs, per-host data sharding, and
ring-based sequence parallelism over XLA collectives (ICI/DCN).
"""

from petastorm_tpu.parallel.mesh import (batch_sharding, host_shard,
                                         make_mesh, replicated_sharding)
from petastorm_tpu.parallel.ring import ring_attention

__all__ = ['make_mesh', 'host_shard', 'batch_sharding', 'replicated_sharding',
           'ring_attention']
