"""GPipe-style pipeline parallelism over a mesh axis.

Stages live one-per-device-group along the ``pipe`` axis; microbatches stream
through the ring: at step ``t`` stage ``s`` computes microbatch ``t - s`` and
``ppermute``s its activation to stage ``s+1`` (XLA lowers the neighbor send to
ICI). The classic pipeline bubble costs ``S-1`` of ``M+S-1`` steps, so
efficiency is ``M/(M+S-1)`` — pick ``n_microbatches >> n_stages``.

The whole schedule is a differentiable ``lax.scan`` (masked selects instead of
data-dependent control flow), so ``jax.grad`` through a pipelined forward
produces the reverse schedule automatically — XLA sees one fused program.

Use under ``jax.shard_map`` with stage-stacked params sharded
``P('pipe', ...)``; :func:`make_pipeline_fn` wraps that plumbing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name: str):
    """Run ``microbatches`` through the pipeline inside a shard_map context.

    :param stage_fn: ``(params_for_one_stage, x) -> y`` with ``y.shape ==
        x.shape`` (inter-stage activations must be shape-stable).
    :param stage_params: this stage's params (leading stage axis already
        squeezed away by the shard_map in_spec).
    :param microbatches: ``(n_micro, mb, ...)`` array, identical on every stage
        (replicated in_spec); only stage 0 actually consumes it.
    :returns: ``(n_micro, mb, ...)`` outputs, identical on every stage.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t; others consume the ring activation
        x0 = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, x0, incoming)
        y = stage_fn(stage_params, x_in)
        # bubble steps compute garbage; mask them out of the output buffer
        out_idx = t - (n_stages - 1)
        is_last = stage == n_stages - 1
        valid = is_last & (out_idx >= 0) & (out_idx < n_micro)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        outputs = outputs.at[idx].set(
            jnp.where(valid, y, outputs[idx]))
        # hand the activation to the next stage (wrap-around send from the
        # last stage is ignored by stage 0's inject select)
        incoming = jax.lax.ppermute(y, axis_name, perm)
        return (incoming, outputs), None

    init_in = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (final_in, outputs), _ = jax.lax.scan(
        step, (init_in, outputs0), jnp.arange(n_micro + n_stages - 1))
    # outputs are populated only on the last stage; share them with every
    # stage so the loss is computable anywhere (single cheap collective)
    outputs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def make_pipeline_fn(stage_fn, mesh, pipe_axis: str = 'pipe',
                     batch_axis: str = None):
    """Wrap :func:`pipeline_apply` in shard_map over ``mesh``.

    Returns ``fn(stacked_params, microbatches) -> outputs`` where
    ``stacked_params`` has a leading ``n_stages`` axis on every leaf (sharded
    over ``pipe_axis``) and ``microbatches`` is ``(n_micro, mb, ...)``.
    With ``batch_axis``, the per-microbatch dim is additionally sharded over
    that axis — pipeline (pp) composed with data parallelism (dp).
    """
    from jax.sharding import PartitionSpec as P

    mb_spec = P(None, batch_axis) if batch_axis else P()

    def fn(stacked_params, microbatches):
        pspecs = jax.tree_util.tree_map(lambda _: P(pipe_axis), stacked_params)

        from petastorm_tpu.parallel.mesh import shard_map_fn

        @functools.partial(
            shard_map_fn(), mesh=mesh,
            in_specs=(pspecs, mb_spec), out_specs=mb_spec)
        def run(stacked, mb):
            # squeeze this stage's slot of the stacked params
            my_params = jax.tree_util.tree_map(lambda a: a[0], stacked)
            if hasattr(jax.lax, 'pcast'):
                mb = jax.lax.pcast(mb, (pipe_axis,), to='varying')
            elif hasattr(jax.lax, 'pvary'):
                # pre-pcast jax: pvary is the older spelling
                mb = jax.lax.pvary(mb, (pipe_axis,))
            # else: pre-vma jax (0.4.x) — shard_map has no varying-axes
            # typing, so there is nothing to cast
            return pipeline_apply(stage_fn, my_params, mb, pipe_axis)

        return run(stacked_params, microbatches)

    return fn
