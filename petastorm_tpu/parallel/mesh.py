"""Mesh construction and sharding helpers.

Idiomatic GSPMD: pick a mesh, annotate shardings, let XLA insert collectives.
Axis vocabulary used across the framework:

- ``data``  — data parallelism (batch dim)
- ``seq``   — sequence/context parallelism (ring attention)
- ``model`` — tensor parallelism (hidden/heads dims)
- ``pipe``  — pipeline stages
- ``expert``— expert parallelism (MoE)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def shard_map_fn():
    """The ``shard_map`` transform across jax versions: promoted to
    ``jax.shard_map`` in newer releases, ``jax.experimental.shard_map`` in
    the 0.4.x line this image pins. One resolution point for every call
    site (ring/pipeline wrappers, the LM's sharded attention).

    On the 0.4.x path the returned callable defaults ``check_rep=False``:
    the kernels in this repo declare their replication through the newer
    varying-mesh-axes (vma) typing, which 0.4.x lacks — its legacy
    replication checker has no rule for ``pallas_call`` at all and would
    reject every Pallas-bearing body outright."""
    import jax
    fn = getattr(jax, 'shard_map', None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map

    def legacy_shard_map(f, **kwargs):
        kwargs.setdefault('check_rep', False)
        return shard_map(f, **kwargs)

    return legacy_shard_map


def make_mesh(axis_sizes: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` with named axes.

    ``axis_sizes`` maps axis name → size; the product must equal the device
    count. Axis order follows dict insertion order: put the fastest-varying
    (innermost, highest-bandwidth) axis last — on TPU that is the axis you want
    riding ICI neighbors, typically ``model``.

    >>> mesh = make_mesh({'data': 2, 'model': 4})
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    sizes = list(axis_sizes.values())
    total = int(np.prod(sizes)) if sizes else 1
    if total != len(devices):
        raise ValueError('Mesh axes {} require {} devices, got {}'.format(
            axis_sizes, total, len(devices)))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axis_sizes.keys()))


def host_shard() -> Tuple[int, int]:
    """(cur_shard, shard_count) for the calling host: each TPU host reads only
    its own row-group shard; sample bytes never cross DCN (SURVEY §5.8)."""
    import jax
    return jax.process_index(), jax.process_count()


def batch_sharding(mesh, batch_axis: str = 'data'):
    """NamedSharding placing dim 0 on the data axis, rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(batch_axis))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec())
