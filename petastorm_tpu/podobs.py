"""Pod-scale observability plane: one surface for cross-host metrics,
read-plane latency, and the pod-wide decode-once certificate.

Every sensor the repo grew so far — spans (``docs/tracing.md``), mergeable
latency histograms (``docs/latency.md``), ``/healthz`` verdicts
(``docs/health.md``), lineage coverage (``docs/lineage.md``), shared-cache
counters (``docs/cache.md``) — stops at the host boundary, while the planes
added by the object-store and pod-cache PRs are explicitly *cross-host*.
This module cashes in the design decision that made PR 12's histograms
bucket-additive: any two hosts' states merge by integer bucket addition, so
a pod-wide p99 carries exactly the same
:data:`~petastorm_tpu.latency.QUANTILE_REL_ERROR_BOUND` as a single host's.

Three pieces:

- **The per-host surface.** :func:`make_observe_fn` builds the
  ``GET /observe/snapshot`` payload a ``DebugServer`` serves: stats
  counters, raw latency-histogram bucket states, the health verdict with
  degraded causes, SLO burn, the lineage coverage digest, shared-cache
  ``global_counters`` (``fills``/``peer_hits``), a span tail, and the
  host's ``time.perf_counter()`` reading (the clock-offset anchor).
- **The aggregator.** :class:`PodObserver` polls a ``host:port`` peer list
  (the same convention as the shared cache's ``peers=``) and merges:
  counters by addition, histograms by bucket-count addition (pod p99s are
  **bit-identical** to direct recording — integer counts have no merge
  order), health by worst-of with per-host causes named, and the pod
  decode-once certificate ``sum(fills) == distinct row groups`` machine-
  checked the way ``CoverageAuditor.assert_complete()`` is. A dead or
  unreachable host degrades the verdict to a **named** :data:`PARTIAL_POD`
  — never a silent shrink of the certificate's denominator.
- **Clock alignment.** Peer HTTP requests and ``/observe`` responses carry
  :data:`TRACE_HEADER` (a request id) and :data:`CLOCK_HEADER` (the
  server's monotonic reading); the observer estimates each host's clock
  offset as ``remote_clock - (t0 + t1) / 2`` so
  :func:`petastorm_tpu.tracing.stitch_pod_trace` can emit one aligned
  timeline across hosts.

Everything is **on by default** and measured within noise
(``BENCH_r19.json``); set ``PETASTORM_TPU_PODOBS=0`` to create no thread,
no routes, and no files: the observe/podmetrics routes 404, the read-plane
span/latency instrumentation compiles out to one boolean test, and no
aggregator state exists anywhere. The observer itself never spawns a
thread — it polls on demand (a call, a CLI run, or an HTTP request to the
``/podmetrics`` route of whichever host embeds it). See
``docs/pod_observability.md``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import sys
import time
import urllib.request
import uuid
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from petastorm_tpu.latency import (LatencyHistogram, NUM_BUCKETS,
                                   QUANTILE_REL_ERROR_BOUND)

logger = logging.getLogger(__name__)

#: Environment variable gating the whole pod-observability plane (default
#: on). ``0``/``false``/``off`` mean: no ``/observe/snapshot`` or
#: ``/podmetrics`` routes, no ``range_fetch``/``peer_fetch`` span or
#: latency recording, no trace headers on peer-cache requests.
PODOBS_ENV_VAR = 'PETASTORM_TPU_PODOBS'

#: Comma-separated ``host:port`` peer list naming the pod's debug
#: endpoints; when set (and the plane is on), the reader embeds a
#: :class:`PodObserver` and serves the aggregate on ``/podmetrics``.
PODOBS_PEERS_ENV_VAR = 'PETASTORM_TPU_PODOBS_PEERS'

#: Request/response header carrying the trace id — one id stamped by the
#: client rides through a peer-cache fetch (and any observe poll) so the
#: per-host span rings can be joined into one pod timeline.
TRACE_HEADER = 'X-Petastorm-Trace'

#: Response header carrying the server's ``time.perf_counter()`` reading
#: at reply time — the clock-offset anchor (monotonic clocks are not
#: comparable across hosts; the offset estimate makes them so).
CLOCK_HEADER = 'X-Petastorm-Clock-S'

#: Route the per-host snapshot is served on (``DebugServer``).
SNAPSHOT_ROUTE = '/observe/snapshot'

#: Route an aggregator host serves the merged pod report on.
PODMETRICS_ROUTE = '/podmetrics'

#: The named degraded verdict when any polled host is unreachable: the
#: report still merges every host that answered, but the certificate
#: refuses to certify against an incomplete denominator.
PARTIAL_POD = 'partial_pod'

#: Pipeline health states from best to worst — must mirror
#: ``petastorm_tpu.health`` (asserted by tests; kept literal here so the
#: pod plane does not import the HTTP/watchdog module).
VERDICT_ORDER = ('healthy', 'degraded', 'starving', 'stalled')

#: Snapshot keys that are NOT mergeable by addition: window spans and
#: fractions would double-count, percentile estimates must come from the
#: merged histograms instead (suffix-matched below).
_NON_ADDITIVE_KEYS = frozenset({'window_s', 'io_overlap_fraction', 'pid',
                                'epoch',
                                # pod-wide constants every elastic host
                                # reports identically: summing K copies
                                # would inflate the certificate denominator
                                'expected_batches'})
_NON_ADDITIVE_SUFFIXES = ('_p50_s', '_p90_s', '_p99_s', '_p999_s',
                          '_fraction')

#: Default poll timeout per peer, matching the shared cache's
#: ``peer_timeout_s`` default.
DEFAULT_TIMEOUT_S = 2.0


def podobs_enabled() -> bool:
    """The :data:`PODOBS_ENV_VAR` gate (default on)."""
    value = os.environ.get(PODOBS_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def pod_peers_from_env() -> Tuple[str, ...]:
    """The :data:`PODOBS_PEERS_ENV_VAR` peer list (empty tuple when
    unset)."""
    return parse_peers(os.environ.get(PODOBS_PEERS_ENV_VAR, ''))


def parse_peers(peers) -> Tuple[str, ...]:
    """Normalize a peer spec — a comma-separated string or an iterable of
    ``host:port`` strings (the shared cache's ``peers=`` convention) —
    into a tuple. Rejects entries without a port: a silent DNS-only entry
    would poll the wrong surface."""
    if peers is None:
        return ()
    if isinstance(peers, str):
        parts: Iterable[str] = peers.split(',')
    else:
        parts = peers
    out = []
    for part in parts:
        part = str(part).strip()
        if not part:
            continue
        if ':' not in part:
            raise ValueError('peer {!r} is not host:port (the shared-cache '
                             'peers= convention)'.format(part))
        out.append(part)
    return tuple(out)


def new_trace_id() -> str:
    """A fresh trace id for :data:`TRACE_HEADER`."""
    return uuid.uuid4().hex


# -- per-host snapshot surface ------------------------------------------------

def make_observe_fn(snapshot_fn: Optional[Callable[[], dict]] = None,
                    health_fn: Optional[Callable[[], dict]] = None,
                    slo_fn: Optional[Callable[[], dict]] = None,
                    coverage_fn: Optional[Callable[[], dict]] = None,
                    cache_counters_fn: Optional[Callable[[], dict]] = None,
                    span_tail_fn: Optional[Callable[[], list]] = None,
                    elastic_fn: Optional[Callable[[], dict]] = None,
                    goodput_fn: Optional[Callable[[], dict]] = None,
                    host: Optional[str] = None) -> Callable[[], dict]:
    """Build the ``observe_fn`` a ``DebugServer`` serves on
    :data:`SNAPSHOT_ROUTE`: one JSON-able dict with every per-host surface
    the pod aggregation consumes. Each section is fenced — a broken sensor
    reports ``{'error': ...}`` in its section instead of killing the whole
    snapshot (the aggregator must keep seeing the healthy sections of a
    sick host)."""
    host = host or socket.gethostname()

    def _section(fn):
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - fence per sensor
            logger.debug('observe snapshot section failed', exc_info=True)
            return {'error': '{}: {}'.format(type(e).__name__, e)}

    def observe() -> dict:
        from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY
        stats = _section(snapshot_fn) or {}
        histograms = {}
        if isinstance(stats, dict):
            stats = dict(stats)
            histograms = stats.pop(LATENCY_HISTOGRAMS_KEY, None) or {}
        snap = {
            'kind': 'petastorm_tpu.observe_snapshot',
            'version': 1,
            'host': host,
            'pid': os.getpid(),
            'clock_s': time.perf_counter(),
            'stats': stats,
            'latency_histograms': histograms,
            'health': _section(health_fn),
            'slo': _section(slo_fn),
            'coverage': _section(coverage_fn),
            'cache': _section(cache_counters_fn),
            'span_tail': _section(span_tail_fn),
            'elastic': _section(elastic_fn),
            'goodput': _section(goodput_fn),
        }
        return snap

    return observe


# -- merge semantics ----------------------------------------------------------

def merge_counters(snapshots: Sequence[Optional[dict]]) -> dict:
    """Merge per-host scalar counters **by addition**, skipping keys that
    are not additive (window spans, fractions, percentile estimates — the
    pod tail comes from :func:`merge_histogram_states`, never from
    averaging per-host percentiles)."""
    totals: Dict[str, float] = {}
    for snap in snapshots:
        for key, value in (snap or {}).items():
            if key.startswith('_') or isinstance(value, bool):
                continue
            if not isinstance(value, (int, float)):
                continue
            if key in _NON_ADDITIVE_KEYS or key.endswith(
                    _NON_ADDITIVE_SUFFIXES):
                continue
            totals[key] = totals.get(key, 0) + value
    return totals


def merge_histogram_states(
        state_maps: Sequence[Optional[Dict[str, dict]]]) -> Dict[str, dict]:
    """Merge per-host ``{stage: state}`` histogram exports (the
    ``LatencyHistogram.state()`` shape) by pure bucket-count addition.
    Because bucket counts are integers over module-fixed boundaries, the
    merge is associative and order-free: the pod histogram is
    **bit-identical** to one histogram that recorded every observation
    directly (the float ``sum`` is addition-order sensitive and therefore
    only approximately equal)."""
    merged: Dict[str, dict] = {}
    for states in state_maps:
        for stage, state in (states or {}).items():
            agg = merged.setdefault(stage, {'buckets': {}, 'sum': 0.0,
                                            'count': 0})
            for index, n in (state.get('buckets') or ()):
                index = min(int(index), NUM_BUCKETS)
                agg['buckets'][index] = agg['buckets'].get(index, 0) + int(n)
            agg['sum'] += float(state.get('sum', 0.0))
            agg['count'] += int(state.get('count', 0))
    return {stage: {'buckets': [[i, n]
                                for i, n in sorted(agg['buckets'].items())
                                if n],
                    'sum': agg['sum'], 'count': agg['count']}
            for stage, agg in merged.items()}


def state_percentiles(state: dict) -> Dict[str, Optional[float]]:
    """p50/p90/p99/p999 of one histogram ``state`` — computed by loading
    the bucket counts into a :class:`~petastorm_tpu.latency.LatencyHistogram`
    so the estimator (and its error bound) is the ONE the per-host plane
    uses, not a reimplementation that could drift."""
    histogram = LatencyHistogram()
    histogram.merge_delta({
        'buckets': {int(i): int(n) for i, n in (state.get('buckets') or ())},
        'sum': float(state.get('sum', 0.0)),
        'count': int(state.get('count', 0))})
    return histogram.percentiles()


def merge_health(verdicts_by_host: Dict[str, Optional[dict]]) -> dict:
    """Worst-of health merge with per-host causes **named**: the pod state
    is the worst per-host state (:data:`VERDICT_ORDER`), and every host's
    own state, hint, and ``degraded_causes`` ride out under ``by_host`` so
    "the pod is degraded" always answers "because host X: <cause>"."""
    worst, worst_rank = VERDICT_ORDER[0], 0
    by_host = {}
    causes: List[str] = []
    for host, verdict in sorted(verdicts_by_host.items()):
        verdict = verdict or {}
        state = verdict.get('state') or VERDICT_ORDER[0]
        try:
            rank = VERDICT_ORDER.index(state)
        except ValueError:
            rank = 1    # unknown state: treat as degraded, never healthy
        host_causes = list(verdict.get('degraded_causes') or [])
        by_host[host] = {'state': state, 'hint': verdict.get('hint'),
                        'causes': host_causes}
        causes.extend('{}: {}'.format(host, c) for c in host_causes)
        if rank > worst_rank:
            worst, worst_rank = state, rank
    return {'state': worst, 'by_host': by_host, 'causes': causes}


class PodCertificateError(AssertionError):
    """The pod decode-once certificate failed (or could not be checked
    against a full denominator). ``AssertionError`` so benchmark/CI
    assertion handling treats it like ``CoverageAuditor.assert_complete``'s
    failures."""


def check_pod_certificate(cache_totals: Optional[dict],
                          expected_row_groups: Optional[int] = None,
                          unreachable: Sequence[str] = (),
                          elastic_totals: Optional[dict] = None,
                          expected_batches: Optional[int] = None) -> dict:
    """Machine-check the pod decode-once certificate from summed
    shared-cache counters: ``sum(fills) == distinct row groups`` (every
    row group decoded exactly once somewhere in the pod), with
    ``peer_hits`` tallied as the dedup evidence. An unreachable host makes
    the certificate **uncheckable** — its fills are missing from the sum,
    so the denominator silently shrank; that is reported as a named
    problem, never as a pass.

    When the elasticity plane is on, ``elastic_totals`` (summed
    ``ElasticHost.elastic_snapshot()`` counters) and ``expected_batches``
    (the lease grid's total) extend the certificate to **exactly-once row
    delivery across membership changes**: ``sum(batches_delivered)`` must
    equal the grid total — more means a batch was delivered twice across a
    rebalance, fewer means one was dropped — with
    ``batches_skipped_claimed`` tallied as the fencing evidence (a takeover
    host that found the batch already claimed and did NOT re-deliver it).
    The per-lease naming of any duplicate/drop (host + path + row group)
    comes from ``podelastic.ElasticCoverageAuditor``."""
    cache_totals = cache_totals or {}
    fills = int(cache_totals.get('fills', 0) or 0)
    peer_hits = int(cache_totals.get('peer_hits', 0) or 0)
    problems: List[str] = []
    unreachable = list(unreachable)
    if unreachable:
        problems.append(
            '{}: {} host(s) unreachable ({}) — their fills are missing '
            'from the sum, so the certificate denominator is incomplete; '
            'refusing to certify'.format(PARTIAL_POD, len(unreachable),
                                         ', '.join(map(str, unreachable))))
    checked = expected_row_groups is not None and not unreachable
    if checked:
        expected = int(expected_row_groups)  # type: ignore[arg-type]
        if fills > expected:
            problems.append(
                'duplicate fills: {} fills recorded for {} distinct row '
                'groups — some row group was decoded more than once '
                '(a forged or double-published fill)'.format(fills,
                                                             expected))
        elif fills < expected:
            problems.append(
                'missing fills: {} fills recorded for {} distinct row '
                'groups — either the run is incomplete or a fill counter '
                'was lost'.format(fills, expected))
    elastic_totals = elastic_totals or {}
    delivered = int(elastic_totals.get('batches_delivered', 0) or 0)
    elastic_checked = expected_batches is not None and not unreachable
    if elastic_checked:
        expected_b = int(expected_batches)  # type: ignore[arg-type]
        if delivered > expected_b:
            problems.append(
                'duplicate delivery: {} batches delivered for a {}-batch '
                'lease grid — some batch was delivered more than once '
                'across a rebalance (the delivery claim fence was '
                'bypassed)'.format(delivered, expected_b))
        elif delivered < expected_b:
            problems.append(
                'dropped delivery: {} batches delivered for a {}-batch '
                'lease grid — a batch was lost across a membership '
                'change'.format(delivered, expected_b))
    ok: Optional[bool]
    if unreachable:
        ok = False
    elif checked or elastic_checked:
        ok = not problems
    else:
        ok = None   # nothing to certify against; never a silent pass
    certificate = {'fills': fills, 'peer_hits': peer_hits,
                   'peer_misses': int(cache_totals.get('peer_misses', 0) or 0),
                   'peer_errors': int(cache_totals.get('peer_errors', 0) or 0),
                   'expected_row_groups': expected_row_groups,
                   'unreachable': unreachable,
                   'checked': checked, 'ok': ok, 'problems': problems}
    if expected_batches is not None or elastic_totals:
        certificate['elastic'] = {
            'batches_delivered': delivered,
            'batches_skipped_claimed': int(
                elastic_totals.get('batches_skipped_claimed', 0) or 0),
            'leases_rebalanced': int(
                elastic_totals.get('leases_rebalanced', 0) or 0),
            'rows_resumed': int(elastic_totals.get('rows_resumed', 0) or 0),
            'expected_batches': expected_batches,
            'checked': elastic_checked,
        }
    return certificate


def check_pod_goodput(goodput_by_host: Optional[Dict[str, Optional[dict]]],
                      min_goodput: Optional[float] = None,
                      unreachable: Sequence[str] = ()) -> dict:
    """The pod goodput verdict from per-host ``/goodput`` summaries
    (``GoodputMonitor.summary()`` shape): the pod fractions are re-derived
    from the SUMMED per-host seconds — never averaged, so a straggler
    cannot hide behind K-1 healthy hosts' means — and the worst-stalling
    host is **named** as the straggler. ``min_goodput`` arms the check
    (the same ``[0, 1]`` target the SLOMonitor takes); an unreachable host
    makes the verdict uncheckable the way :func:`check_pod_certificate`'s
    is — a named :data:`PARTIAL_POD` refusal, never a silent pass."""
    totals = {'steps': 0, 'fenced_steps': 0, 'total_s': 0.0, 'stall_s': 0.0,
              'h2d_s': 0.0, 'device_s': 0.0, 'host_s': 0.0}
    by_host: Dict[str, dict] = {}
    for host, section in sorted((goodput_by_host or {}).items()):
        state = (section or {}).get('state') or {}
        total = float(state.get('total_s', 0.0) or 0.0)
        if total <= 0.0:
            continue
        stall = float(state.get('stall_s', 0.0) or 0.0)
        h2d = float(state.get('h2d_s', 0.0) or 0.0)
        device = float(state.get('device_s', 0.0) or 0.0)
        totals['steps'] += int(state.get('steps', 0) or 0)
        totals['fenced_steps'] += int(state.get('fenced_steps', 0) or 0)
        totals['total_s'] += total
        totals['stall_s'] += stall
        totals['h2d_s'] += h2d
        totals['device_s'] += device
        totals['host_s'] += float(state.get('host_s', 0.0) or 0.0)
        by_host[host] = {
            'steps': int(state.get('steps', 0) or 0),
            'goodput_fraction': round(device / total, 4),
            'data_stall_fraction': round((stall + h2d) / total, 4),
        }
    pod_total = totals['total_s']
    goodput_fraction = (round(totals['device_s'] / pod_total, 4)
                        if pod_total > 0 else None)
    data_stall_fraction = (
        round((totals['stall_s'] + totals['h2d_s']) / pod_total, 4)
        if pod_total > 0 else None)
    straggler = None
    if by_host:
        worst = max(by_host, key=lambda h: by_host[h]['data_stall_fraction'])
        straggler = dict(by_host[worst], host=worst)
    problems: List[str] = []
    unreachable = list(unreachable)
    if unreachable:
        problems.append(
            '{}: {} host(s) unreachable ({}) — their step seconds are '
            'missing from the sum; refusing to certify pod goodput'.format(
                PARTIAL_POD, len(unreachable),
                ', '.join(map(str, unreachable))))
    checked = (min_goodput is not None and goodput_fraction is not None
               and not unreachable)
    if checked and goodput_fraction < float(min_goodput):  # type: ignore[arg-type]
        detail = ''
        if straggler is not None:
            detail = (' — straggler {}: data_stall_fraction {}, '
                      'goodput_fraction {}'.format(
                          straggler['host'],
                          straggler['data_stall_fraction'],
                          straggler['goodput_fraction']))
        problems.append('pod goodput {} below min_goodput {}{}'.format(
            goodput_fraction, float(min_goodput), detail))
    ok: Optional[bool]
    if unreachable:
        ok = False
    elif checked:
        ok = not problems
    else:
        ok = None   # no target or no data; never a silent pass
    return {'goodput_fraction': goodput_fraction,
            'data_stall_fraction': data_stall_fraction,
            'totals': totals, 'by_host': by_host, 'straggler': straggler,
            'min_goodput': min_goodput, 'unreachable': unreachable,
            'checked': checked, 'ok': ok, 'problems': problems}


# -- the aggregator -----------------------------------------------------------

class PodObserver:
    """Poll a pod's per-host ``/observe/snapshot`` surfaces and merge them
    into one report.

    Embeddable (a reader serves :meth:`report` on ``/podmetrics`` when
    :data:`PODOBS_PEERS_ENV_VAR` names the pod), scriptable
    (``petastorm-tpu-podstat`` — :func:`main`), and callable from
    benchmarks/tests. Never spawns a thread: every poll happens on the
    caller's thread, so the kill switch truly means "no pod-plane
    machinery exists".

    ``expected_row_groups`` arms the decode-once certificate;
    :meth:`assert_certificate` raises :class:`PodCertificateError` the way
    ``CoverageAuditor.assert_complete`` raises on a coverage hole."""

    def __init__(self, peers, timeout_s: float = DEFAULT_TIMEOUT_S,
                 expected_row_groups: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 expected_batches: Optional[int] = None,
                 min_goodput: Optional[float] = None):
        self.peers = parse_peers(peers)
        if not self.peers:
            raise ValueError('PodObserver needs at least one host:port peer')
        self.timeout_s = float(timeout_s)
        self.expected_row_groups = expected_row_groups
        self.expected_batches = expected_batches
        #: Arms the pod goodput verdict (:func:`check_pod_goodput`): the
        #: pod-wide goodput fraction (re-derived from summed seconds) must
        #: meet this floor, with the straggler host named on breach.
        self.min_goodput = min_goodput
        self.trace_id = trace_id or new_trace_id()
        self.last_report: Optional[dict] = None

    # -- polling ---------------------------------------------------------------

    def fetch_snapshot(self, peer: str) -> dict:
        """Fetch one peer's snapshot, annotating it with the poll metadata:
        ``_peer``, ``_rtt_s``, and ``_clock_offset_s`` — the estimate
        ``remote_clock - (t0 + t1) / 2``, i.e. what to ADD to a local
        ``perf_counter`` reading to land on that host's clock (good to
        about half the RTT)."""
        url = 'http://{}{}'.format(peer, SNAPSHOT_ROUTE)
        request = urllib.request.Request(
            url, headers={TRACE_HEADER: self.trace_id})
        t0 = time.perf_counter()
        with urllib.request.urlopen(request,
                                    timeout=self.timeout_s) as response:
            body = response.read()
            t1 = time.perf_counter()
            clock_header = response.headers.get(CLOCK_HEADER)
        snapshot = json.loads(body.decode('utf-8'))
        remote_clock = None
        if clock_header:
            try:
                remote_clock = float(clock_header)
            except ValueError:
                remote_clock = None
        if remote_clock is None:
            remote_clock = snapshot.get('clock_s')
        snapshot['_peer'] = peer
        snapshot['_rtt_s'] = t1 - t0
        snapshot['_clock_offset_s'] = (
            remote_clock - (t0 + t1) / 2.0
            if isinstance(remote_clock, (int, float)) else None)
        return snapshot

    def poll(self) -> Tuple[List[dict], List[dict]]:
        """``(snapshots, unreachable)``: every peer that answered, and a
        named ``{'peer', 'error'}`` record for every one that did not."""
        snapshots, unreachable = [], []
        for peer in self.peers:
            try:
                snapshots.append(self.fetch_snapshot(peer))
            except Exception as e:  # noqa: BLE001 - a dead peer is a verdict
                unreachable.append({'peer': peer,
                                    'error': '{}: {}'.format(
                                        type(e).__name__, e)})
        return snapshots, unreachable

    # -- merging ---------------------------------------------------------------

    def merge(self, snapshots: List[dict],
              unreachable: Optional[List[dict]] = None) -> dict:
        """Merge polled snapshots into the pod report (pure function of its
        inputs — tests drive it with simulated hosts, no HTTP needed)."""
        unreachable = list(unreachable or [])
        hosts = []
        health_by_host: Dict[str, Optional[dict]] = {}
        stats_list, histogram_maps, cache_list = [], [], []
        elastic_list: List[Optional[dict]] = []
        goodput_by_host: Dict[str, Optional[dict]] = {}
        slo_burns: Dict[str, float] = {}
        hard_breach_hosts: List[str] = []
        coverage_by_host = {}
        trace_tracks = []
        for snapshot in snapshots:
            label = str(snapshot.get('_peer') or snapshot.get('host'))
            health = snapshot.get('health')
            hosts.append({
                'peer': snapshot.get('_peer'),
                'host': snapshot.get('host'),
                'pid': snapshot.get('pid'),
                'rtt_s': snapshot.get('_rtt_s'),
                'clock_offset_s': snapshot.get('_clock_offset_s'),
                'state': (health or {}).get('state'),
            })
            health_by_host[label] = health
            stats_list.append(snapshot.get('stats'))
            histogram_maps.append(snapshot.get('latency_histograms'))
            cache_list.append(snapshot.get('cache'))
            elastic_list.append(snapshot.get('elastic'))
            goodput = snapshot.get('goodput')
            if goodput is not None:
                goodput_by_host[label] = goodput
            slo = snapshot.get('slo') or {}
            burn = slo.get('burn_rate')
            if isinstance(burn, (int, float)):
                slo_burns[label] = float(burn)
            if slo.get('hard_breach'):
                hard_breach_hosts.append(label)
            coverage = snapshot.get('coverage')
            if coverage is not None:
                coverage_by_host[label] = coverage
            span_tail = snapshot.get('span_tail')
            if span_tail:
                trace_tracks.append({
                    'host': label,
                    'pid': snapshot.get('pid'),
                    'clock_offset_s': snapshot.get('_clock_offset_s'),
                    'spans': span_tail,
                })
        merged_histograms = merge_histogram_states(histogram_maps)
        latency = {}
        for stage, state in sorted(merged_histograms.items()):
            entry = {'count': state['count'],
                     'sum_s': round(state['sum'], 6)}
            for name, value in state_percentiles(state).items():
                entry[name + '_s'] = (round(value, 9)
                                      if value is not None else None)
            latency[stage] = entry
        health = merge_health(health_by_host)
        cache_totals = merge_counters(cache_list)
        elastic_totals = merge_counters(elastic_list)
        certificate = check_pod_certificate(
            cache_totals, self.expected_row_groups,
            unreachable=[u['peer'] for u in unreachable],
            elastic_totals=elastic_totals,
            expected_batches=self.expected_batches)
        goodput = check_pod_goodput(
            goodput_by_host, min_goodput=self.min_goodput,
            unreachable=[u['peer'] for u in unreachable])
        verdict = PARTIAL_POD if unreachable else health['state']
        report = {
            'kind': 'petastorm_tpu.podmetrics',
            'version': 1,
            'trace_id': self.trace_id,
            'peers': list(self.peers),
            'hosts': hosts,
            'hosts_reporting': len(snapshots),
            'unreachable': unreachable,
            'verdict': verdict,
            'health': health,
            'counters': merge_counters(stats_list),
            'latency': latency,
            'latency_histograms': merged_histograms,
            'quantile_rel_error_bound': QUANTILE_REL_ERROR_BOUND,
            'slo': {'burn_rate_by_host': slo_burns,
                    'worst_burn_rate': (max(slo_burns.values())
                                        if slo_burns else None),
                    'hard_breach_hosts': hard_breach_hosts},
            'coverage': coverage_by_host,
            'cache': {'totals': cache_totals,
                      'by_host': {str(h.get('peer') or h.get('host')):
                                  c for h, c in zip(hosts, cache_list)
                                  if c is not None}},
            'elastic': {'totals': elastic_totals,
                        'by_host': {str(h.get('peer') or h.get('host')):
                                    e for h, e in zip(hosts, elastic_list)
                                    if e is not None}},
            'certificate': certificate,
            'goodput': goodput,
            'trace_tracks': trace_tracks,
        }
        self.last_report = report
        return report

    def report(self) -> dict:
        """One poll + merge round: THE pod report (also what an aggregator
        host serves on :data:`PODMETRICS_ROUTE`)."""
        snapshots, unreachable = self.poll()
        return self.merge(snapshots, unreachable)

    def assert_certificate(self, report: Optional[dict] = None) -> dict:
        """Machine-check the decode-once certificate of ``report`` (or of a
        fresh :meth:`report`): raises :class:`PodCertificateError` naming
        every problem — duplicate/missing fills, or an unreachable host
        that makes the denominator incomplete."""
        if report is None:
            report = self.report()
        certificate = report.get('certificate') or {}
        if certificate.get('ok') is True:
            return certificate
        problems = list(certificate.get('problems') or [])
        if certificate.get('ok') is None:
            problems.append('certificate unchecked: pass '
                            'expected_row_groups to arm it')
        raise PodCertificateError(
            'pod decode-once certificate failed: ' + '; '.join(problems))

    def export_pod_chrome_trace(self, path: str,
                                report: Optional[dict] = None) -> str:
        """Stitch the polled hosts' span tails into one clock-aligned
        chrome trace (``chrome://tracing`` / Perfetto) at ``path``."""
        if report is None:
            report = self.last_report or self.report()
        from petastorm_tpu.tracing import stitch_pod_trace
        return stitch_pod_trace(report.get('trace_tracks') or [], path)


# -- CLI ----------------------------------------------------------------------

def main(argv=None) -> int:
    """``petastorm-tpu-podstat``: poll a pod's debug endpoints and print
    the merged report. Exits 1 on a :data:`PARTIAL_POD` verdict or (with
    ``--expect-row-groups``) a failed certificate — scriptable the way
    ``/healthz`` status codes are."""
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-podstat',
        description='Aggregate pod-wide petastorm-tpu observability: poll '
                    'each host\'s /observe/snapshot and merge counters, '
                    'latency histograms, health, and the decode-once '
                    'certificate onto one surface.')
    parser.add_argument('peers', nargs='?', default=None,
                        help='comma-separated host:port list of debug '
                             'endpoints (default: ${})'.format(
                                 PODOBS_PEERS_ENV_VAR))
    parser.add_argument('--timeout', type=float, default=DEFAULT_TIMEOUT_S,
                        help='per-peer poll timeout in seconds '
                             '(default %(default)s)')
    parser.add_argument('--expect-row-groups', type=int, default=None,
                        help='arm the decode-once certificate: the number '
                             'of distinct row groups the pod must have '
                             'decoded exactly once')
    parser.add_argument('--min-goodput', type=float, default=None,
                        help='arm the pod goodput verdict: the pod-wide '
                             'goodput fraction (summed seconds, straggler '
                             'named) must meet this [0, 1] floor')
    parser.add_argument('--trace-out', default=None,
                        help='also write the stitched pod chrome trace '
                             'to this path')
    parser.add_argument('--compact', action='store_true',
                        help='single-line JSON output')
    args = parser.parse_args(argv)
    peers = args.peers or os.environ.get(PODOBS_PEERS_ENV_VAR, '')
    if not parse_peers(peers):
        parser.error('no peers: pass host:port[,host:port...] or set '
                     '{}'.format(PODOBS_PEERS_ENV_VAR))
    observer = PodObserver(peers, timeout_s=args.timeout,
                           expected_row_groups=args.expect_row_groups,
                           min_goodput=args.min_goodput)
    report = observer.report()
    print(json.dumps(report, indent=None if args.compact else 2,
                     sort_keys=True, default=str))
    if args.trace_out:
        observer.export_pod_chrome_trace(args.trace_out, report)
        print('pod trace written to {}'.format(args.trace_out),
              file=sys.stderr)
    if report['verdict'] == PARTIAL_POD:
        return 1
    if args.expect_row_groups is not None:
        try:
            observer.assert_certificate(report)
        except PodCertificateError as e:
            print(str(e), file=sys.stderr)
            return 1
    if args.min_goodput is not None:
        goodput = report.get('goodput') or {}
        if goodput.get('ok') is not True:
            for problem in goodput.get('problems') or (
                    'pod goodput unchecked: no host reported step data',):
                print(problem, file=sys.stderr)
            return 1
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
