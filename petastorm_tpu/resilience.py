"""Unified resilient IO: one retry policy and one hedging primitive for the
whole read path.

Before this module, retry logic lived as two ad-hoc islands —
``fs.retry_filesystem_call`` (fixed-step exponential backoff, no jitter, no
total-wall cap, retried *permanent* errors) and the HDFS namenode failover
loop (``hdfs/namenode.py``) — and nothing at all protected the hot
``read_row_group`` path that actually moves the bytes. This module is the
substrate all three now share, plus the tail-latency weapon none of them had:

- :class:`RetryPolicy` — error classification (transient vs permanent),
  exponential backoff with **full jitter** (a fleet of readers hitting one
  flaky store must not synchronize into retry storms — fixed-step backoff
  from many clients does exactly that), and a **total wall budget** so a
  retried call can never consume unbounded time.
- :class:`HedgedRead` — fire a duplicate read when the first exceeds the
  live p95 of recent read latencies (the classic tail-at-scale move): first
  result wins, the loser is cancelled (its result discarded, its thread
  abandoned as a daemon). Hedging trades a small amount of extra load for a
  large cut in p99 — measured in ``BENCH_r16.json``.
- :class:`ResilientIO` — the worker-facing bundle wiring both under
  ``piece_worker._read_row_group`` and the readahead thread, accumulating
  the ``io_retries`` / ``io_hedges`` / ``io_hedge_wins`` /
  ``io_permanent_failures`` counters that flow to ``ReaderStats`` (and from
  there to ``/metrics``, ``/diagnostics`` and flight records).

Classification contract: ``OSError`` with a *request-shaped* errno
(``ENOENT``/``EACCES``/``EISDIR``/... — the path is wrong, not the store)
is **permanent** and fails on the first attempt; every other
``OSError``/``IOError`` (connection resets, EIO, timeouts) is transient.
``classify_read_error`` additionally treats pyarrow parse errors as
transient: a truncated/short read from flaky storage corrupts the Arrow
stream mid-parse, and a re-read from a healthy replica succeeds — a
*persistently* corrupt file still fails after the bounded attempts.

See ``docs/robustness.md`` for the fault model and knob tables.
"""

from __future__ import annotations

import errno
import logging
import random
import threading
import time
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)

TRANSIENT, PERMANENT = 'transient', 'permanent'

#: ``OSError`` errnos that describe the *request*, not the store: retrying
#: cannot help, and a bad path must fail in one attempt (satellite fix: the
#: old ``retry_filesystem_call`` retried these 3 times with delays).
PERMANENT_ERRNOS = frozenset({
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
    errno.EEXIST, errno.ENOSPC, errno.EROFS, errno.ENAMETOOLONG,
})

#: ``OSError`` subclasses that are permanent regardless of errno (they are
#: raised by pure-python filesystems that never set one).
PERMANENT_TYPES = (FileNotFoundError, PermissionError, IsADirectoryError,
                   NotADirectoryError, FileExistsError)

#: Default retry knobs (the ``retry=True`` shape readers resolve to).
DEFAULT_RETRY = dict(attempts=3, initial_backoff_s=0.05, max_backoff_s=2.0,
                     total_budget_s=30.0)

#: Default hedge knobs (the ``hedge=True`` shape). ``threshold_s=None``
#: means adaptive: hedge when a read exceeds the rolling p95 of recent
#: reads times ``threshold_scale`` (clamped to [min, max]).
DEFAULT_HEDGE = dict(threshold_s=None, threshold_scale=2.0,
                     min_threshold_s=0.005, max_threshold_s=5.0,
                     warmup_samples=8)


def classify_error(exc: BaseException) -> str:
    """``'transient'`` (worth retrying) or ``'permanent'`` for a filesystem
    error. Non-OSError exceptions are permanent by default — a codec bug
    must not burn the retry budget."""
    if isinstance(exc, PERMANENT_TYPES):
        return PERMANENT
    if isinstance(exc, (OSError, IOError)):
        if getattr(exc, 'errno', None) in PERMANENT_ERRNOS:
            return PERMANENT
        return TRANSIENT
    return PERMANENT


def classify_read_error(exc: BaseException) -> str:
    """:func:`classify_error` plus: pyarrow parse failures are transient.
    A short/truncated read from a flaky store corrupts the Arrow stream and
    surfaces as ``ArrowInvalid`` — re-reading fetches clean bytes. Bounded
    attempts keep a genuinely corrupt file failing fast."""
    verdict = classify_error(exc)
    if verdict == TRANSIENT:
        return verdict
    if type(exc).__module__.startswith('pyarrow'):
        return TRANSIENT
    return verdict


def resolve_retry(retry) -> Optional[dict]:
    """Normalize a factory ``retry=`` knob: ``True``/``None`` → the default
    policy, ``False``/``0`` → ``None`` (off), a dict → defaults overlaid
    (typo'd keys fail the factory)."""
    if retry is None or retry is True:
        return dict(DEFAULT_RETRY)
    if retry is False or retry == 0:
        return None
    if isinstance(retry, dict):
        unknown = set(retry) - set(DEFAULT_RETRY)
        if unknown:
            raise ValueError('unknown retry option(s) {}; valid: {}'.format(
                sorted(unknown), sorted(DEFAULT_RETRY)))
        return dict(DEFAULT_RETRY, **retry)
    raise ValueError('retry must be True/False or an options dict, got '
                     '{!r}'.format(retry))


#: Default worker auto-recovery knobs (the ``worker_recovery=True`` shape).
#: ``max_respawns=None`` resolves to ``max(3, workers_count)`` at pool
#: start; ``poison_threshold`` is how many worker deaths one item may be
#: implicated in before it is quarantined through the lineage channel;
#: ``settle_s`` is how long the process pool waits for surviving workers to
#: drain before declaring the remaining in-flight items lost.
DEFAULT_RECOVERY = dict(max_respawns=None, poison_threshold=3, settle_s=1.0)


def resolve_recovery(recovery) -> Optional[dict]:
    """Normalize a factory ``worker_recovery=`` knob: ``True``/``None`` →
    defaults (recovery is ON by default — a crashed worker becomes a
    respawn + redispatch, not a dead pipeline), ``False`` → ``None`` (a
    worker death stops the pool loudly, the pre-recovery behavior), a dict
    → defaults overlaid."""
    if recovery is None or recovery is True:
        return dict(DEFAULT_RECOVERY)
    if recovery is False or recovery == 0:
        return None
    if isinstance(recovery, dict):
        unknown = set(recovery) - set(DEFAULT_RECOVERY)
        if unknown:
            raise ValueError('unknown worker_recovery option(s) {}; valid: '
                             '{}'.format(sorted(unknown),
                                         sorted(DEFAULT_RECOVERY)))
        return dict(DEFAULT_RECOVERY, **recovery)
    raise ValueError('worker_recovery must be True/False or an options '
                     'dict, got {!r}'.format(recovery))


def resolve_hedge(hedge) -> Optional[dict]:
    """Normalize a factory ``hedge=`` knob: ``False``/``None``/``0`` → off,
    ``True`` → adaptive defaults, a number → fixed threshold seconds, a
    dict → defaults overlaid."""
    if hedge is None or hedge is False or hedge == 0:
        return None
    if hedge is True:
        return dict(DEFAULT_HEDGE)
    if isinstance(hedge, (int, float)):
        if hedge < 0:
            raise ValueError('hedge threshold must be >= 0, got '
                             '{!r}'.format(hedge))
        return dict(DEFAULT_HEDGE, threshold_s=float(hedge))
    if isinstance(hedge, dict):
        unknown = set(hedge) - set(DEFAULT_HEDGE)
        if unknown:
            raise ValueError('unknown hedge option(s) {}; valid: {}'.format(
                sorted(unknown), sorted(DEFAULT_HEDGE)))
        return dict(DEFAULT_HEDGE, **hedge)
    raise ValueError('hedge must be True/False, a threshold in seconds, or '
                     'an options dict, got {!r}'.format(hedge))


class RetryPolicy:
    """Bounded retry with full-jitter exponential backoff.

    :param attempts: total tries (1 = no retry).
    :param initial_backoff_s: backoff ceiling before the first retry; the
        ceiling doubles per attempt up to ``max_backoff_s``. The actual
        sleep is uniform in ``[0, ceiling]`` (**full jitter**) so
        simultaneous failures across readers decorrelate instead of
        re-arriving in lockstep.
    :param max_backoff_s: backoff ceiling cap.
    :param total_budget_s: total wall budget across all attempts + sleeps;
        when spent, the last error is raised even with attempts remaining
        (``None`` = unbounded — only the attempt count limits).
    :param classify: ``exc -> 'transient'|'permanent'``.
    :param seed: seed for the jitter RNG (tests pin it; production uses OS
        entropy).
    """

    def __init__(self, attempts: int = 3, initial_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 total_budget_s: Optional[float] = 30.0,
                 classify: Callable[[BaseException], str] = classify_error,
                 seed: Optional[int] = None):
        if attempts < 1:
            raise ValueError('attempts must be >= 1, got {}'.format(attempts))
        self.attempts = attempts
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.total_budget_s = total_budget_s
        self.classify = classify
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """The jittered sleep before retry number ``attempt`` (0-based)."""
        ceiling = min(self.max_backoff_s,
                      self.initial_backoff_s * (2 ** attempt))
        return self._rng.uniform(0.0, ceiling)

    def call(self, fn, *args,
             on_retry: Optional[Callable[[BaseException, int], None]] = None,
             on_event: Optional[Callable[[str, int], None]] = None,
             description: str = '', **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.

        ``on_retry(exc, attempt)`` runs before each backoff sleep (the HDFS
        wrapper rotates namenodes there; the row-group reader drops its
        possibly-poisoned file handle). ``on_event(name, n)`` receives
        ``'io_retries'`` / ``'io_permanent_failures'`` counter increments.
        Raises the last underlying error (permanent errors immediately, on
        the first attempt)."""
        deadline = (time.monotonic() + self.total_budget_s
                    if self.total_budget_s is not None else None)
        for attempt in range(self.attempts):
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                if self.classify(e) == PERMANENT:
                    if on_event is not None:
                        on_event('io_permanent_failures', 1)
                    raise
                last_attempt = attempt == self.attempts - 1
                out_of_budget = (deadline is not None
                                 and time.monotonic() >= deadline)
                if last_attempt or out_of_budget:
                    raise
                if on_event is not None:
                    on_event('io_retries', 1)
                if on_retry is not None:
                    on_retry(e, attempt)
                delay = self.backoff_s(attempt)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - time.monotonic()))
                logger.warning('%s failed (%s: %s); retry %d/%d in %.3fs',
                               description or getattr(fn, '__name__', 'call'),
                               type(e).__name__, e, attempt + 1,
                               self.attempts - 1, delay)
                if delay > 0:
                    time.sleep(delay)
        raise AssertionError('unreachable')  # pragma: no cover


class _HedgeRace:
    """Shared state of one primary-vs-hedge race: first finisher publishes,
    the loser's result is discarded."""

    __slots__ = ('done', 'winner', 'value', 'error', '_lock')

    def __init__(self):
        self.done = threading.Event()
        self.winner: Optional[str] = None
        self.value = None
        self.error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def finish(self, who: str, value=None, error=None) -> bool:
        with self._lock:
            if self.winner is not None:
                return False
            self.winner = who
            self.value = value
            self.error = error
        self.done.set()
        return True


class AdaptiveThreshold:
    """Rolling p95 of recent durations — the live hedge trigger.

    A small ring of the last N observations; :meth:`current` is the p95
    scaled by ``threshold_scale``, clamped to ``[min, max]``, and ``None``
    until ``warmup_samples`` observations exist (hedging before the
    distribution is known would double every read)."""

    __slots__ = ('_lock', '_ring', '_size', '_pos', '_count', '_scale',
                 '_min_s', '_max_s', '_warmup')

    def __init__(self, scale: float = 2.0, min_s: float = 0.005,
                 max_s: float = 5.0, warmup: int = 8, size: int = 128):
        self._lock = threading.Lock()
        self._ring = [0.0] * size
        self._size = size
        self._pos = 0
        self._count = 0
        self._scale = scale
        self._min_s = min_s
        self._max_s = max_s
        self._warmup = max(1, warmup)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self._ring[self._pos] = seconds
            self._pos = (self._pos + 1) % self._size
            self._count += 1

    def current(self) -> Optional[float]:
        with self._lock:
            n = min(self._count, self._size)
            if self._count < self._warmup:
                return None
            values = sorted(self._ring[:n])
        p95 = values[min(n - 1, int(0.95 * n))]
        return min(self._max_s, max(self._min_s, p95 * self._scale))


class HedgedRead:
    """Tail-latency hedging: run the primary read on a helper thread; when
    it exceeds the live threshold, fire a *second* identical read (through
    an independent handle — parquet handles are not concurrency-safe) and
    take whichever finishes first.

    The loser is cancelled by discard: its thread (daemon, fire-and-forget)
    keeps running until its blocking read returns, then finds the race
    decided and drops the result. That is the only cancellation semantics a
    blocking filesystem read allows — and it bounds *latency*, which is the
    point; the wasted read is the documented cost of hedging.
    """

    def __init__(self, options: dict,
                 on_event: Optional[Callable[[str, int], None]] = None,
                 on_attempt: Optional[Callable[[dict], None]] = None):
        self._fixed_threshold = options.get('threshold_s')
        self._threshold = AdaptiveThreshold(
            scale=options.get('threshold_scale', 2.0),
            min_s=options.get('min_threshold_s', 0.005),
            max_s=options.get('max_threshold_s', 5.0),
            warmup=options.get('warmup_samples', 8))
        self._on_event = on_event
        #: Per-attempt observability hook: called once per finished attempt
        #: (winner AND abandoned loser) with ``{'tag', 'start_s', 'dur_s',
        #: 'won', 'cancelled_by_hedge', 'description'}`` — the loser of a
        #: decided race is the attempt hedging cancelled, which counters
        #: alone cannot show (satellite: BENCH_r18's "0 hedges fired" claim
        #: must be visible in a trace). May be called from a race thread;
        #: the callback must be thread-safe.
        self._on_attempt = on_attempt
        # live race threads (winners AND abandoned losers): drained at
        # shutdown so no thread is still inside a C read when the
        # interpreter finalizes
        self._live_lock = threading.Lock()
        self._live: set = set()

    def drain(self, timeout_s: float = 5.0) -> None:
        """Join every outstanding race thread (bounded): an abandoned loser
        blocked in a C-level read must finish (or be given up on) before
        its interpreter starts finalizing."""
        deadline = time.monotonic() + timeout_s
        with self._live_lock:
            threads = list(self._live)
        for thread in threads:
            thread.join(timeout=max(0.0, deadline - time.monotonic()))

    def threshold_s(self) -> Optional[float]:
        if self._fixed_threshold is not None:
            return self._fixed_threshold
        return self._threshold.current()

    def _event(self, name: str, n: int = 1) -> None:
        if self._on_event is not None:
            self._on_event(name, n)

    def _report_attempt(self, tag: str, start_s: float, won: bool,
                        description: str) -> None:
        """Fire :attr:`_on_attempt` for one finished attempt. Losing an
        already-decided race is the cancelled-by-hedge annotation: the only
        way an attempt loses is that its twin won first."""
        if self._on_attempt is None:
            return
        try:
            self._on_attempt({'tag': tag, 'start_s': start_s,
                              'dur_s': time.perf_counter() - start_s,
                              'won': bool(won),
                              'cancelled_by_hedge': not won,
                              'description': description})
        except Exception:  # observability must never fail the read
            logger.debug('hedge on_attempt callback failed', exc_info=True)

    def call(self, primary_fn, hedge_fn=None, description: str = 'read'):
        """Run ``primary_fn()``; if it is still running after the live
        threshold, also run ``hedge_fn()`` (defaults to ``primary_fn``) on a
        second thread and return the first result. Exceptions from the
        winner propagate; a losing failure is discarded (the race was
        already decided by a success), but if the FIRST finisher failed, its
        error wins — hedging is a latency tool, not a retry layer (wrap
        with :class:`RetryPolicy` for that)."""
        threshold = self.threshold_s()
        if threshold is None:
            # warmup: run inline, observe, never hedge
            start = time.perf_counter()
            value = primary_fn()
            self._threshold.observe(time.perf_counter() - start)
            self._report_attempt('primary', start, True, description)
            return value
        race = _HedgeRace()
        start = time.perf_counter()

        def run(tag, fn):
            attempt_start = time.perf_counter()
            try:
                try:
                    value = fn()
                except BaseException as e:  # noqa: BLE001 - winner re-raises
                    won = race.finish(tag, error=e)
                else:
                    won = race.finish(tag, value=value)
                    if tag == 'hedge':
                        self._event('io_hedge_wins' if won
                                    else 'io_hedge_losses')
                self._report_attempt(tag, attempt_start, won, description)
            finally:
                with self._live_lock:
                    self._live.discard(threading.current_thread())

        def spawn(tag, fn):
            thread = threading.Thread(
                target=run, args=(tag, fn), daemon=True,
                name='petastorm-tpu-hedge-{}'.format(tag))
            with self._live_lock:
                self._live.add(thread)
            thread.start()
            return thread

        spawn('primary', primary_fn)
        hedged = False
        if not race.done.wait(threshold):
            hedged = True
            self._event('io_hedges')
            spawn('hedge', hedge_fn or primary_fn)
            race.done.wait()
        elapsed = time.perf_counter() - start
        if not hedged:
            # only un-hedged reads feed the threshold: a hedged read's
            # duration is already capped by the race and would drag the
            # p95 toward the threshold itself
            self._threshold.observe(elapsed)
        if race.error is not None:
            raise race.error
        return race.value


class ResilientIO:
    """The worker-facing bundle: retry + hedge + thread-safe counters.

    One instance per worker; the worker thread and its background readahead
    thread both route reads through :meth:`read`, and the worker thread
    drains the accumulated counters via :meth:`take_events` (same
    discipline as the shared cache's event drain — ``record_count`` is not
    safe from the background thread)."""

    #: Bound on undrained attempt spans: a direct construction that never
    #: drains (benchmarks, tests) must not grow without limit.
    MAX_PENDING_SPANS = 2048

    def __init__(self, retry_options: Optional[dict] = None,
                 hedge_options: Optional[dict] = None,
                 classify: Callable[[BaseException], str] = classify_read_error,
                 seed: Optional[int] = None,
                 observe_spans: bool = False):
        self.retry = (RetryPolicy(classify=classify, seed=seed,
                                  **retry_options)
                      if retry_options else None)
        self._observe_spans = bool(observe_spans)
        self.hedge = (HedgedRead(hedge_options, on_event=self._count,
                                 on_attempt=(self._record_attempt
                                             if self._observe_spans
                                             else None))
                      if hedge_options else None)
        self._lock = threading.Lock()
        self._events: Dict[str, int] = {}
        # (name, cat, start_s, dur_s, args) tuples — the WorkerBase
        # record_span shape, drained by the worker thread
        self._spans: list = []

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._events[name] = self._events.get(name, 0) + n

    def _record_attempt(self, info: dict) -> None:
        """Accumulate one hedge-race attempt as a span tuple (called from
        race threads — lock-protected, bounded)."""
        args = {'attempt': info.get('tag'),
                'description': info.get('description'),
                'won': bool(info.get('won'))}
        if info.get('cancelled_by_hedge'):
            args['cancelled_by_hedge'] = True
        span = ('io_attempt', 'io', info.get('start_s'),
                info.get('dur_s'), args)
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.MAX_PENDING_SPANS:
                del self._spans[:len(self._spans) - self.MAX_PENDING_SPANS]

    def take_events(self) -> Dict[str, int]:
        """Drain the accumulated counter deltas (worker thread only)."""
        with self._lock:
            events, self._events = self._events, {}
        return events

    def take_spans(self) -> list:
        """Drain the accumulated per-attempt span tuples (worker thread
        only; empty unless constructed with ``observe_spans=True``)."""
        with self._lock:
            spans, self._spans = self._spans, []
        return spans

    def drain(self, timeout_s: float = 5.0) -> None:
        """Join outstanding hedge race threads (worker shutdown): an
        abandoned loser must not still be inside a C-level read when its
        interpreter finalizes."""
        if self.hedge is not None:
            self.hedge.drain(timeout_s)

    @property
    def enabled(self) -> bool:
        return self.retry is not None or self.hedge is not None

    def read(self, fn, hedge_fn=None, on_retry=None,
             description: str = 'read'):
        """Run one read under the configured hedge (inner) and retry
        (outer) layers: a hedged pair that *both* fail is one failed
        attempt, retried with backoff through fresh handles."""
        call = fn
        if self.hedge is not None:
            hedger = self.hedge

            def call():
                return hedger.call(fn, hedge_fn=hedge_fn,
                                   description=description)
        if self.retry is None:
            return call()
        return self.retry.call(call, on_retry=on_retry, on_event=self._count,
                               description=description)
