"""Read datasets written by original petastorm (pickled-metadata compat).

The reference pickles its ``Unischema`` into ``_common_metadata`` under
``dataset-toolkit.unischema.v1`` (``etl/dataset_metadata.py:194-205`` — its own
TODO admits the pickle-ABI fragility). This framework stores JSON instead, but
a user migrating from petastorm has datasets with pickled metadata on disk.

This module decodes those pickles **without petastorm installed** and without
executing arbitrary pickle payloads: a restricted unpickler maps the known
petastorm/pyspark class paths onto inert shim classes (plus numpy/stdlib
basics) and rejects everything else. The shims are then converted to native
:class:`petastorm_tpu.unischema.Unischema` / codec objects.

Legacy package names (``av.experimental.deepdrive.dataset_toolkit``,
``dataset_toolkit`` — reference ``etl/legacy.py:22-47``) are handled by
suffix-matching module paths.
"""

from __future__ import annotations

import io
import pickle
from collections import OrderedDict
from decimal import Decimal

import numpy as np

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  NdarrayCodec, ScalarCodec)
from petastorm_tpu.errors import PetastormMetadataError
from petastorm_tpu.unischema import Unischema, UnischemaField

#: the reference's metadata keys (``etl/dataset_metadata.py:34-35``)
PETASTORM_UNISCHEMA_KEY = b'dataset-toolkit.unischema.v1'
PETASTORM_ROW_GROUPS_PER_FILE_KEY = b'dataset-toolkit.num_row_groups_per_file.v1'


class _Shim(object):
    """Inert stand-in: pickle restores attributes into __dict__ / __setstate__
    without running any constructor logic."""

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self.__dict__['_state'] = state


def _make_shim(name):
    return type(name, (_Shim,), {'_shim_name': name})


class _UnischemaFieldShim(tuple):
    """Reference UnischemaField is a NamedTuple(name, numpy_dtype, shape,
    codec, nullable); pickle rebuilds it as class(*values)."""

    def __new__(cls, *args):
        if len(args) == 1 and isinstance(args[0], (tuple, list)):
            args = tuple(args[0])
        return super(_UnischemaFieldShim, cls).__new__(cls, args)


_PETASTORM_MODULE_SUFFIXES = ('petastorm.unischema', 'petastorm.codecs',
                              'dataset_toolkit.unischema', 'dataset_toolkit.codecs')

_ALLOWED_STDLIB = {
    ('collections', 'OrderedDict'): OrderedDict,
    ('decimal', 'Decimal'): Decimal,
    ('builtins', 'set'): set,
    ('builtins', 'frozenset'): frozenset,
    ('builtins', 'list'): list,
    ('builtins', 'dict'): dict,
    ('builtins', 'tuple'): tuple,
    # str/bytes TYPE objects appear as field numpy_dtype for string fields;
    # protocol-2 pickles (py2-era petastorm) spell them __builtin__.unicode/str
    ('builtins', 'str'): str,
    ('builtins', 'bytes'): bytes,
    ('__builtin__', 'unicode'): str,
    ('__builtin__', 'str'): bytes,
}

_CLASS_SHIMS = {
    'Unischema': _make_shim('Unischema'),
    'UnischemaField': _UnischemaFieldShim,
    'ScalarCodec': _make_shim('ScalarCodec'),
    'NdarrayCodec': _make_shim('NdarrayCodec'),
    'CompressedNdarrayCodec': _make_shim('CompressedNdarrayCodec'),
    'CompressedImageCodec': _make_shim('CompressedImageCodec'),
}


#: numpy globals legitimately present in pickled dtypes/scalars/arrays —
#: nothing else from numpy (np.save, np.fromfile, ... are attack surface)
_NUMPY_ALLOWED_NAMES = {'dtype', 'ndarray', 'scalar', '_reconstruct',
                        '_frombuffer'}


def _numpy_global(module, name):
    allowed = name in _NUMPY_ALLOWED_NAMES
    if not allowed:
        # numpy scalar type classes (int32, float64, bool_, datetime64, ...)
        attr = getattr(np, name, None)
        allowed = isinstance(attr, type) and issubclass(attr, np.generic)
    if not allowed:
        raise pickle.UnpicklingError(
            'Refusing to unpickle numpy global {}.{}'.format(module, name))
    return getattr(__import__(module, fromlist=[name]), name)


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        # numpy internals used when numpy scalars/dtypes are pickled
        if module in ('numpy', 'numpy.core.multiarray', 'numpy._core.multiarray',
                      'numpy.core.numeric', 'numpy._core.numeric'):
            return _numpy_global(module, name)
        if (module, name) in _ALLOWED_STDLIB:
            return _ALLOWED_STDLIB[(module, name)]
        if module.startswith('pyspark.'):
            # spark type instances ride inside ScalarCodec; keep them inert
            return _make_shim('pyspark:{}'.format(name))
        if any(module.endswith(sfx) for sfx in _PETASTORM_MODULE_SUFFIXES) \
                and name in _CLASS_SHIMS:
            return _CLASS_SHIMS[name]
        raise pickle.UnpicklingError(
            'Refusing to unpickle {}.{} from petastorm metadata (not in the '
            'compat allowlist)'.format(module, name))


def _convert_codec(codec_shim):
    if codec_shim is None:
        return None
    kind = getattr(codec_shim, '_shim_name', None)
    if kind == 'ScalarCodec':
        return ScalarCodec()
    if kind == 'NdarrayCodec':
        return NdarrayCodec()
    if kind == 'CompressedNdarrayCodec':
        return CompressedNdarrayCodec()
    if kind == 'CompressedImageCodec':
        # reference stores '.png'/'.jpeg' + quality (codecs.py:59-66)
        fmt = getattr(codec_shim, '_image_codec', '.png').lstrip('.')
        quality = int(getattr(codec_shim, '_quality', 80))
        if fmt in ('jpg', 'jpeg'):
            return CompressedImageCodec('jpeg', quality=quality)
        return CompressedImageCodec(fmt)
    raise PetastormMetadataError(
        'Unknown codec {!r} in petastorm metadata'.format(kind))


def _convert_field(field_shim) -> UnischemaField:
    name, numpy_dtype, shape, codec, nullable = (tuple(field_shim) + (None, False))[:5]
    return UnischemaField(str(name), numpy_dtype,
                          tuple(shape) if shape is not None else (),
                          _convert_codec(codec), bool(nullable))


def unischema_from_petastorm_pickle(payload: bytes) -> Unischema:
    """Decode a pickled reference ``Unischema`` into a native one."""
    try:
        shell = _RestrictedUnpickler(io.BytesIO(payload)).load()
    except pickle.UnpicklingError:
        raise
    except Exception as e:
        raise PetastormMetadataError(
            'Could not decode pickled petastorm unischema: {}'.format(e)) from e
    fields_dict = getattr(shell, '_fields', None)
    if not fields_dict:
        raise PetastormMetadataError(
            'Pickled petastorm unischema carries no fields')
    name = getattr(shell, '_name', 'petastorm_schema')
    fields = [_convert_field(f) for f in fields_dict.values()]
    return Unischema(str(name), fields)
