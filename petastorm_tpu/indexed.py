"""Deterministic random-access loader with O(1) exact resume.

The reference cannot resume mid-epoch at all (``reader.py:468-492``; SURVEY
§5.4), and any queue-based pool makes the stream order scheduling-dependent.
This module takes the TPU-native route (the design Grain uses for the same
problem): **batch b of epoch e is a pure function of (dataset, seed, e, b)**.

- :class:`IndexedDatasetReader` gives random-access decoded reads over the
  row groups of a petastorm_tpu dataset (LRU row-group cache, columnar
  decode — no per-row Python).
- :class:`IndexedBatchLoader` derives a per-epoch window-shuffled permutation
  of global row indices from ``(seed, epoch)``, slices it into fixed batches,
  prefetches upcoming batches on a thread pool **by index**, and reorders
  results — so pool scheduling cannot perturb the stream. Killing the loader
  and restoring ``state_dict()`` elsewhere reproduces the remaining stream
  byte-for-byte, in O(1) (no replay).

Window shuffling bounds decode amplification: rows are shuffled within
windows of ``shuffle_window_groups`` consecutive row groups (window order
also shuffled), so a batch touches at most a few row groups while the
window size controls shuffle quality — the knob ``shuffle_row_drop_partitions``
approximates in the queue-based reader (reference ``reader.py:61-96``).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from petastorm_tpu.errors import NoDataAvailableError
from petastorm_tpu.etl.dataset_metadata import (infer_or_load_unischema,
                                                load_row_groups)
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dataset_url_or_urls
from petastorm_tpu.readers.columnar_worker import _column_to_numpy
from petastorm_tpu.unischema import match_unischema_fields
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.thread_pool import ThreadPool

from petastorm_tpu.workers.ventilator import BackPressuredVentilator
from petastorm_tpu.workers.worker_base import WorkerBase

logger = logging.getLogger(__name__)


class IndexedDatasetReader:
    """Random-access decoded reads over a petastorm_tpu dataset.

    ``read_piece(i)`` returns the decoded columns of row group ``i`` (through
    a bounded LRU cache); global row index arithmetic is exposed via
    ``row_offsets`` / ``total_rows``. Thread-safe.
    """

    def __init__(self, dataset_url: str, schema_fields: Optional[List[str]] = None,
                 storage_options=None, cache_groups: int = 8):
        dataset_url = normalize_dataset_url_or_urls(dataset_url)
        fs, path, _ = get_filesystem_and_path_or_paths(dataset_url, storage_options)
        if isinstance(path, list):
            raise ValueError('IndexedDatasetReader needs a single dataset url')
        self._filesystem = fs
        self._path = path
        # Foreign parquet stores (no petastorm metadata) work too: the schema
        # is inferred from the arrow footer and row counts come from the
        # per-footer scan in load_row_groups.
        stored_schema, _ = infer_or_load_unischema(fs, path)
        #: full stored schema — predicates may reference fields outside the
        #: output view (matches the streaming readers' semantics)
        self.full_schema = stored_schema
        if schema_fields is not None:
            matched = match_unischema_fields(stored_schema, schema_fields)
            if not matched:
                raise ValueError('schema_fields {} matched no fields'.format(
                    schema_fields))
            self.schema = stored_schema.create_schema_view(matched)
        else:
            self.schema = stored_schema
        self.pieces = load_row_groups(fs, path)
        if not self.pieces:
            raise NoDataAvailableError('No row groups at {}'.format(path))
        if any(p.num_rows < 0 for p in self.pieces):
            raise ValueError('IndexedDatasetReader needs per-row-group row '
                             'counts (regenerate dataset metadata)')
        counts = np.asarray([p.num_rows for p in self.pieces], np.int64)
        #: row_offsets[i] = global index of the first row of piece i
        self.row_offsets = np.concatenate([[0], np.cumsum(counts)])
        self.total_rows = int(self.row_offsets[-1])

        # keyed by (piece_index, fields-tuple-or-None): narrowed and full
        # reads of one piece never alias
        self._cache: 'collections.OrderedDict[tuple, Dict[str, np.ndarray]]' = \
            collections.OrderedDict()
        self._cache_groups = cache_groups
        self._lock = threading.Lock()
        # parquet readers are NOT safe for concurrent reads on one instance:
        # every pool thread gets its own handles (cf. readers/piece_worker.py)
        self._local = threading.local()
        self._open_files: List = []

    # -- io --------------------------------------------------------------------

    def _parquet_file(self, path: str):
        import pyarrow.parquet as pq
        files = getattr(self._local, 'files', None)
        if files is None:
            files = self._local.files = {}
        pf = files.get(path)
        if pf is None:
            handle = self._filesystem.open(path, 'rb')
            try:
                pf = pq.ParquetFile(handle)
            except Exception:
                handle.close()   # bad footer etc. must not leak the fd
                raise
            files[path] = pf
            with self._lock:
                self._open_files.append(handle)
        return pf

    def close(self):
        """Close all parquet file handles opened by any thread."""
        with self._lock:
            handles, self._open_files = self._open_files, []
        self._local = threading.local()
        for handle in handles:
            try:
                handle.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()

    def read_piece(self, piece_index: int,
                   fields: Optional[tuple] = None) -> Dict[str, np.ndarray]:
        """Decoded columns of row group ``piece_index``.

        ``fields`` (a tuple of names from the FULL schema) narrows the read
        to those columns — callers like the NGram window loader read a
        different column set than the dataset's output view without mutating
        shared state; the LRU cache keys on (piece, fields) so narrowed and
        full reads never alias."""
        cache_key = (piece_index, fields)
        with self._lock:
            cached = self._cache.get(cache_key)
            if cached is not None:
                self._cache.move_to_end(cache_key)
                return cached
        piece = self.pieces[piece_index]
        lookup = self.schema.fields if fields is None else self.full_schema.fields
        names = list(self.schema.fields.keys()) if fields is None else list(fields)
        partition_keys = set(piece.partition_dict.keys())
        stored = [n for n in names if n not in partition_keys]
        table = self._parquet_file(piece.path).read_row_group(
            piece.row_group, columns=stored)
        columns = {}
        for name in names:
            if name in table.column_names:
                columns[name] = _column_to_numpy(table.column(name),
                                                 lookup[name])
        from petastorm_tpu.utils import cast_partition_value
        for key, value in piece.partition_dict.items():
            if key in lookup and (fields is None or key in names):
                field = lookup[key]
                typed = cast_partition_value(field.numpy_dtype, value)
                if isinstance(typed, str):
                    col = np.empty(table.num_rows, dtype=object)
                    col[:] = typed
                else:
                    col = np.full(table.num_rows, typed)
                columns[key] = col
        with self._lock:
            self._cache[cache_key] = columns
            while len(self._cache) > self._cache_groups:
                self._cache.popitem(last=False)
        return columns

    def gather(self, global_rows: np.ndarray,
               fields: Optional[tuple] = None) -> Dict[str, np.ndarray]:
        """Decoded columns for the given global row indices, in order.

        ``fields`` narrows the read to those columns (see
        :meth:`read_piece`)."""
        piece_ids = np.searchsorted(self.row_offsets, global_rows,
                                    side='right') - 1
        local = global_rows - self.row_offsets[piece_ids]
        out: Dict[str, np.ndarray] = {}
        for p in np.unique(piece_ids):
            mask = piece_ids == p
            cols = self.read_piece(int(p), fields)
            idx = local[mask]
            for name, col in cols.items():
                if name not in out:
                    out[name] = np.empty((len(global_rows),) + col.shape[1:],
                                         dtype=col.dtype)
                elif out[name].dtype != col.dtype:
                    # pieces can decode the same field to different dtypes —
                    # a nullable int column is int64 in null-free groups but
                    # NaN-holed float in null-bearing ones; assigning into
                    # the first piece's dtype would cast NaN to garbage ints
                    if out[name].dtype.kind == 'O' or col.dtype.kind == 'O':
                        promoted = np.dtype(object)
                    else:
                        promoted = np.promote_types(out[name].dtype,
                                                    col.dtype)
                    if promoted != out[name].dtype:
                        out[name] = out[name].astype(promoted)
                out[name][mask] = col[idx]
        return out

    def scan_columns(self, fields):
        """Yield ``(piece_index, {field: decoded column}, n_rows)`` for every
        piece, decoding ONLY ``fields`` (names from the full schema;
        partition-derived columns synthesized) — the one-pass scan behind
        predicate evaluation and the NGram window index build.

        The scan opens its own short-lived handles (closed on exit, even on
        error) rather than registering into the reader's shared handle list:
        the dataset object may be shared with live loaders whose in-flight
        reads a close() would corrupt."""
        import pyarrow.parquet as pq

        from petastorm_tpu.readers.columnar_worker import make_partition_columns
        fields = sorted(set(fields))
        scan_files: Dict[str, tuple] = {}
        try:
            for piece_index, piece in enumerate(self.pieces):
                partition_keys = set(piece.partition_dict.keys())
                stored = [n for n in fields if n not in partition_keys]
                n = piece.num_rows
                cols: Dict[str, np.ndarray] = {}
                if stored:
                    entry = scan_files.get(piece.path)
                    if entry is None:
                        handle = self._filesystem.open(piece.path, 'rb')
                        try:
                            entry = (pq.ParquetFile(handle), handle)
                        except Exception:
                            handle.close()
                            raise
                        scan_files[piece.path] = entry
                    table = entry[0].read_row_group(piece.row_group,
                                                    columns=stored)
                    n = table.num_rows
                    for name in stored:
                        cols[name] = _column_to_numpy(
                            table.column(name), self.full_schema.fields[name])
                cols.update(make_partition_columns(self.full_schema, piece, n,
                                                   set(fields)))
                yield piece_index, cols, n
        finally:
            for _, handle in scan_files.values():
                try:
                    handle.close()
                except OSError:
                    pass

    def evaluate_predicate(self, predicate) -> np.ndarray:
        """Global indices of the rows ``predicate`` includes, in dataset order.

        Runs ONCE (decoding only the predicate's fields, bypassing the
        row-group cache) so the surviving row set is fixed up front — the
        indexed loader's deterministic batch grid needs a known row universe,
        unlike the streaming readers' per-row-group pushdown
        (``readers/columnar_worker.py:_load_with_predicate``). Validated
        against the FULL stored schema: predicates may use fields outside the
        ``schema_fields`` view, like the streaming readers allow."""
        from petastorm_tpu.readers.columnar_worker import (
            predicate_row_mask, validate_predicate_fields)
        fields = validate_predicate_fields(predicate, self.full_schema)
        surviving = []
        for piece_index, cols, n in self.scan_columns(fields):
            mask = predicate_row_mask(predicate, fields, cols, n)
            surviving.append(self.row_offsets[piece_index]
                             + np.nonzero(mask)[0])
        if not surviving:
            return np.empty(0, np.int64)
        return np.concatenate(surviving).astype(np.int64)


def epoch_permutation(total_rows: int, row_offsets: np.ndarray, seed, epoch: int,
                      shuffle: bool = True,
                      shuffle_window_groups: int = 4) -> np.ndarray:
    """The (seed, epoch)-deterministic global row order: shuffle row-group
    window order, then rows within each window."""
    if not shuffle:
        return np.arange(total_rows, dtype=np.int64)
    rng = np.random.default_rng((seed, epoch))
    n_pieces = len(row_offsets) - 1
    group_order = rng.permutation(n_pieces)
    out = []
    for start in range(0, n_pieces, shuffle_window_groups):
        window = group_order[start:start + shuffle_window_groups]
        idx = np.concatenate([np.arange(row_offsets[g], row_offsets[g + 1],
                                        dtype=np.int64) for g in window])
        rng.shuffle(idx)
        out.append(idx)
    return np.concatenate(out) if out else np.empty(0, np.int64)


def _next_cursor(epoch: int, batch: int, batches_per_epoch: int):
    """The (epoch, batch) grid successor — single source of truth for the
    ventilator, the consumer's expected order, and the checkpoint cursor."""
    batch += 1
    return (epoch, batch) if batch < batches_per_epoch else (epoch + 1, 0)


class _ScheduleVentilator(BackPressuredVentilator):
    """Lazily ventilates the (epoch, batch) grid from a cursor.

    O(1) memory regardless of ``num_epochs x batches_per_epoch`` — a
    materialized schedule (list of tuples + list of kwargs dicts) for a large
    dataset over many epochs would be gigabytes of resident Python objects
    before the first batch is produced."""

    def __init__(self, ventilate_fn, start_epoch: int, start_batch: int,
                 num_epochs: int, batches_per_epoch: int, max_in_flight: int):
        super().__init__(ventilate_fn, max_in_flight=max_in_flight)
        self._start = (start_epoch, start_batch)
        self._num_epochs = num_epochs
        self._bpe = batches_per_epoch
        if start_epoch >= num_epochs:
            self._completed.set()

    def _ventilate_loop(self):
        e, b = self._start
        while e < self._num_epochs and not self._stop_event.is_set():
            if not self._acquire_slot():
                return
            self._ventilate_fn(epoch=e, batch=b)
            e, b = _next_cursor(e, b, self._bpe)


class _IndexedBatchWorker(WorkerBase):
    """Assembles ventilated (epoch, batch) items into column batches."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._loader = args['loader']

    def process(self, epoch: int, batch: int):
        columns = self._loader._assemble(epoch, batch)
        self.publish_func((epoch, batch, columns))


class IndexedBatchLoader:
    """Deterministic batch stream with O(1) exact checkpoint/resume.

    Yields dicts of numpy column arrays of exactly ``batch_size`` rows
    (``drop_last`` is forced: deterministic indexing needs a fixed batch
    grid; the tail rows of an epoch rotate in via the next epoch's shuffle).

    :param seed: with ``shuffle=True``, the stream is a pure function of
        (dataset, seed); two loaders with equal parameters yield identical
        streams regardless of worker scheduling.
    :param workers_count: thread-pool width prefetching batches by index.
    :param prefetch_batches: bound on assembled-but-unconsumed batches.

    Checkpointing::

        state = loader.state_dict()          # {'epoch': e, 'batch': b}
        ...
        restored = IndexedBatchLoader(same_args...)
        restored.load_state_dict(state)
        for batch in restored:               # continues exactly at (e, b)
            ...
    """

    def __init__(self, dataset: IndexedDatasetReader, batch_size: int,
                 num_epochs: int = 1, seed: int = 0, shuffle: bool = True,
                 shuffle_window_groups: int = 4, workers_count: int = 4,
                 prefetch_batches: int = 8, predicate=None,
                 transform_spec=None, pad_spec=None):
        if num_epochs is None:
            raise ValueError('IndexedBatchLoader needs a finite num_epochs '
                             '(the resume cursor indexes a finite schedule)')
        self._dataset = dataset
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self.seed = seed
        self.shuffle = shuffle
        self.shuffle_window_groups = shuffle_window_groups
        self.workers_count = workers_count
        self.prefetch_batches = prefetch_batches
        self.predicate = predicate
        self.transform_spec = transform_spec
        if transform_spec is not None:
            from petastorm_tpu.transform import transform_schema
            self.schema = transform_schema(dataset.schema, transform_spec)
        else:
            self.schema = dataset.schema
        # ragged (wildcard-shape) fields pad to dense bucketed arrays inside
        # the deterministic batch function, so exact resume covers them too
        # (same spec grammar as JaxDataLoader; pads run AFTER transform_spec)
        from petastorm_tpu.jax_utils import (check_pad_spec_fields,
                                             validate_pad_spec)
        self.pad_spec = validate_pad_spec(pad_spec)
        check_pad_spec_fields(self.pad_spec, self.schema.fields,
                              'IndexedBatchLoader')
        if predicate is not None:
            # The surviving row set is fixed ONCE here; the stream stays a
            # pure function of (dataset, predicate, seed, cursor), so resume
            # semantics are unchanged. Window shuffling then operates on the
            # per-piece offsets of the SURVIVORS. (The scan manages its own
            # short-lived file handles — nothing leaks on failure, and a
            # shared dataset's live handles are untouched.)
            self._selection = dataset.evaluate_predicate(predicate)
            self._perm_offsets = np.searchsorted(
                self._selection, dataset.row_offsets, side='left')
            total = len(self._selection)
        else:
            self._selection = None
            self._perm_offsets = dataset.row_offsets
            total = dataset.total_rows
        self.total_rows = int(total)
        self.batches_per_epoch = total // batch_size
        if self.batches_per_epoch == 0:
            raise NoDataAvailableError(
                'Dataset has {} rows{} < batch_size {}'.format(
                    total, ' (after predicate)' if predicate else '',
                    batch_size))
        self.epoch = 0
        self.batch = 0
        self._perm_cache: 'collections.OrderedDict[int, np.ndarray]' = \
            collections.OrderedDict()
        self._perm_lock = threading.Lock()
        # pools whose join() timed out with a thread still alive; their
        # deferred dataset.close() is retried once the threads are gone
        self._stale_pools: List = []

    # -- deterministic addressing ---------------------------------------------

    def _permutation(self, epoch: int) -> np.ndarray:
        with self._perm_lock:
            perm = self._perm_cache.get(epoch)
            if perm is not None:
                return perm
        perm = epoch_permutation(self.total_rows,
                                 self._perm_offsets, self.seed, epoch,
                                 self.shuffle, self.shuffle_window_groups)
        with self._perm_lock:
            self._perm_cache[epoch] = perm
            while len(self._perm_cache) > 2:
                self._perm_cache.popitem(last=False)
        return perm

    def _batch_rows(self, epoch: int, batch: int) -> np.ndarray:
        """Global row indices of batch ``batch`` in epoch ``epoch`` — the one
        place batch addressing lives (the sharded subclass sub-slices it).
        With a predicate, permutation positions index the SURVIVOR list and
        map back to dataset row indices here."""
        positions = self._permutation(epoch)[batch * self.batch_size:
                                             (batch + 1) * self.batch_size]
        if self._selection is not None:
            return self._selection[positions]
        return positions

    def _apply_transform(self, columns: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Columnar TransformSpec contract (shared with the streaming
        columnar worker via ``apply_columnar_transform``). Deterministic
        because the transform is a pure per-batch function of deterministic
        input."""
        if self.transform_spec is not None:
            from petastorm_tpu.transform import apply_columnar_transform
            columns = apply_columnar_transform(self.transform_spec,
                                               self.schema, columns)
        if self.pad_spec:
            from petastorm_tpu.jax_utils import pad_ragged_batch
            columns = pad_ragged_batch(columns, self.pad_spec)
        return columns

    def _assemble(self, epoch: int, batch: int) -> Dict[str, np.ndarray]:
        return self._apply_transform(
            self._dataset.gather(self._batch_rows(epoch, batch)))

    # -- checkpoint state ------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        """Cursor of the NEXT batch to yield; O(1) to save and restore."""
        return {'epoch': self.epoch, 'batch': self.batch, 'version': 1}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        """Restore the cursor; rejects missing/unknown ``version`` and
        missing cursor keys loudly (the checkpoint.py contract — resuming
        from a garbage dict must fail at load, not misposition training)."""
        if not isinstance(state, dict):
            raise ValueError('loader state must be a dict, got '
                             '{!r}'.format(type(state).__name__))
        if 'version' not in state:
            raise ValueError("loader state has no 'version' key — it was "
                             'not produced by state_dict() (keys: '
                             '{})'.format(sorted(state)))
        if state['version'] != 1:
            raise ValueError('Unknown state version {!r} (this build reads '
                             'version 1)'.format(state['version']))
        missing = [k for k in ('epoch', 'batch') if k not in state]
        if missing:
            raise ValueError('loader state is missing key(s) {} (keys '
                             'present: {})'.format(missing, sorted(state)))
        self.epoch = int(state['epoch'])
        self.batch = int(state['batch'])
        if self.batch >= self.batches_per_epoch:
            self.epoch += self.batch // self.batches_per_epoch
            self.batch = self.batch % self.batches_per_epoch

    # -- iteration -------------------------------------------------------------

    def _sweep_stale_pools(self) -> bool:
        """Drop stale pools whose threads have since exited; True if any
        remain alive (closing the dataset under them would be unsafe)."""
        self._stale_pools = [
            p for p in self._stale_pools
            if any(t.is_alive() for t in getattr(p, '_threads', []))]
        return bool(self._stale_pools)

    def close(self, stale_thread_grace_s: float = 5.0):
        """Close the underlying dataset's parquet handles (reopened lazily on
        any later read).

        If a previous iteration's pool join timed out leaving a zombie worker
        thread, waits up to ``stale_thread_grace_s`` for it to exit, then
        closes anyway (an explicit close must release the fds; the zombie's
        in-flight read surfaces an error rather than leaking handles)."""
        deadline = time.monotonic() + stale_thread_grace_s
        while self._sweep_stale_pools():
            if time.monotonic() >= deadline:
                logger.warning(
                    'Closing indexed dataset with %d stale worker thread(s) '
                    'still alive after %.1fs grace; their in-flight reads '
                    'may fail',
                    sum(t.is_alive()
                        for p in self._stale_pools
                        for t in getattr(p, '_threads', [])),
                    stale_thread_grace_s)
                self._stale_pools = []
                break
            time.sleep(0.05)
        self._dataset.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()

    def _schedule(self, start_epoch, start_batch):
        e, b = start_epoch, start_batch
        while e < self.num_epochs:
            yield e, b
            e, b = _next_cursor(e, b, self.batches_per_epoch)

    def __iter__(self):
        if self.epoch >= self.num_epochs:
            return
        # retry any close deferred by a previous iteration whose pool join
        # timed out with a live thread (avoids fd accumulation on loaders
        # iterated repeatedly without close()/context-manager use)
        if self._stale_pools and not self._sweep_stale_pools():
            self._dataset.close()
        pool = ThreadPool(self.workers_count,
                          results_queue_size=self.prefetch_batches)
        ventilator = _ScheduleVentilator(
            pool.ventilate, self.epoch, self.batch, self.num_epochs,
            self.batches_per_epoch,
            max_in_flight=self.workers_count + self.prefetch_batches)
        pool.start(_IndexedBatchWorker, {'loader': self}, ventilator)
        stash: Dict[tuple, Dict[str, np.ndarray]] = {}
        try:
            for expected in self._schedule(self.epoch, self.batch):
                while expected not in stash:
                    epoch, batch, columns = pool.get_results()
                    stash[(epoch, batch)] = columns
                columns = stash.pop(expected)
                e, b = expected
                # advance cursor BEFORE yielding: state saved while the
                # consumer holds this batch points at the next one
                self.epoch, self.batch = _next_cursor(
                    e, b, self.batches_per_epoch)
                yield columns
        except EmptyResultError:
            raise RuntimeError('worker pool drained before schedule finished')
        finally:
            pool.stop()
            pool.join()
            # release the fds the worker threads opened (the next iteration's
            # fresh threads open their own) — but only once the threads are
            # really gone: join() times out rather than verifying exit, and
            # closing a file under a zombie reader corrupts its last read
            if any(t.is_alive() for t in getattr(pool, '_threads', [])):
                self._stale_pools.append(pool)   # close retried later
            else:
                self._dataset.close()


def sharded_batch_setup(mesh, batch_axis: str, batch_size: int):
    """Validate a global batch against a mesh axis and derive this process's
    ``(NamedSharding, local_positions)``.

    Positions come from the sharding's own device→index map — NOT from
    process_index block arithmetic: topology-permuted meshes
    (``mesh_utils.create_device_mesh``) can place a process's devices at
    non-contiguous global offsets, and
    ``make_array_from_process_local_data`` lays local data out by that map.
    Shared by the sharded row and NGram loaders."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    nproc = jax.process_count()
    if batch_size % nproc:
        raise ValueError('global batch_size {} must divide evenly over {} '
                         'processes'.format(batch_size, nproc))
    n_shards = int(mesh.shape[batch_axis])
    if batch_size % n_shards:
        raise ValueError(
            'global batch_size {} must divide evenly over the {} devices '
            "of mesh axis '{}'".format(batch_size, n_shards, batch_axis))
    sharding = NamedSharding(mesh, PartitionSpec(batch_axis))
    idx_map = sharding.addressable_devices_indices_map((batch_size,))
    positions = set()
    for (sl,) in idx_map.values():
        positions.update(range(*sl.indices(batch_size)))
    return sharding, np.asarray(sorted(positions), np.int64)


class ShardedIndexedLoader(IndexedBatchLoader):
    """Deterministic GSPMD loader: O(1) exact resume + global ``jax.Array``
    batches over a mesh.

    ``batch_size`` is the GLOBAL batch. Every process derives the same
    (seed, epoch, batch)-addressed permutation slice and gathers only the
    rows at the global positions its mesh devices own (from the sharding's
    device→index map); the sub-batches assemble into global arrays via
    ``jax.make_array_from_process_local_data``. Because the
    schedule is a pure function of the cursor, all hosts stay in lockstep and
    a restored ``state_dict()`` resumes the identical global stream —
    deterministic, preemption-safe multi-host input (the composition of this
    framework's two departures from the reference: the indexed loader and the
    GSPMD adapter). Resuming with a different ``process_count`` changes which
    rows land on which host but not the global batches.

    String/object columns cannot live in HBM; they ride under
    ``batch['_host']`` as this process's local sub-batch.
    """

    def __init__(self, dataset: IndexedDatasetReader, batch_size: int,
                 mesh, batch_axis: str = 'data', **kwargs):
        sharding, local_positions = sharded_batch_setup(mesh, batch_axis,
                                                        batch_size)
        super().__init__(dataset, batch_size, **kwargs)
        from petastorm_tpu.jax_utils import require_single_bucket_pad_spec
        require_single_bucket_pad_spec(self.pad_spec, 'ShardedIndexedLoader')
        self.mesh = mesh
        self.batch_axis = batch_axis
        self._sharding = sharding
        self._local_positions = local_positions

    def _assemble(self, epoch: int, batch: int) -> Dict[str, np.ndarray]:
        rows = self._batch_rows(epoch, batch)
        # NOTE: the transform runs per-host on this process's local sub-batch,
        # so it must be ROW-WISE (e.g. decode/resize); a transform that mixes
        # rows (batch statistics) would see only the local shard.
        return self._apply_transform(
            self._dataset.gather(rows[self._local_positions]))

    def __iter__(self):
        from petastorm_tpu.jax_utils import stage_to_global
        for local_batch in super().__iter__():
            yield stage_to_global(local_batch, self._sharding)


def make_indexed_loader(dataset_url, batch_size, num_epochs=1, seed=0,
                        shuffle=True, shuffle_window_groups=4,
                        workers_count=4, prefetch_batches=8,
                        schema_fields=None, storage_options=None,
                        cache_groups=None, mesh=None, batch_axis='data',
                        predicate=None, transform_spec=None, pad_spec=None):
    """Factory: :class:`IndexedDatasetReader` + :class:`IndexedBatchLoader`
    (host numpy batches), or :class:`ShardedIndexedLoader` (global
    ``jax.Array`` batches over ``mesh``, ``batch_size`` global).

    Works on foreign parquet stores too (schema inferred, row counts from
    footers). ``predicate`` fixes the surviving row set once at construction;
    ``transform_spec`` applies the columnar transform contract per batch —
    both preserve the pure-function-of-cursor resume guarantee."""
    dataset = IndexedDatasetReader(
        dataset_url, schema_fields=schema_fields,
        storage_options=storage_options,
        cache_groups=(cache_groups if cache_groups is not None
                      else max(8, shuffle_window_groups + workers_count)))
    kwargs = dict(num_epochs=num_epochs, seed=seed, shuffle=shuffle,
                  shuffle_window_groups=shuffle_window_groups,
                  workers_count=workers_count,
                  prefetch_batches=prefetch_batches,
                  predicate=predicate, transform_spec=transform_spec,
                  pad_spec=pad_spec)
    if mesh is None:
        return IndexedBatchLoader(dataset, batch_size, **kwargs)
    return ShardedIndexedLoader(dataset, batch_size, mesh=mesh,
                                batch_axis=batch_axis, **kwargs)
