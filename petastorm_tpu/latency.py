"""Tail-latency plane: mergeable streaming histograms and a declarative SLO
monitor.

Every number the pipeline exported before this module — ``ReaderStats`` sums,
``/metrics`` gauges, the roofline model's ceilings — is an aggregate: a mean,
a total, a rate. A training infeed that is fast *on average* but stalls the
device every hundredth batch is invisible to all of them, and that is exactly
the failure mode a worker-pool + bounded-queue architecture produces under
contention. This module adds the distribution layer:

- :class:`LatencyHistogram` — a lock-cheap, log-bucketed streaming histogram
  over **fixed geometric bucket boundaries** (module-level constants), so any
  two instances are mergeable by plain bucket-count addition: worker-side
  delta accumulators, cross-process shipping, and rolling windows all reduce
  to integer adds. Quantiles (p50/p90/p99/p999) are estimated by geometric
  interpolation inside the covering bucket with a worst-case relative error
  bounded by the bucket growth factor (:data:`QUANTILE_REL_ERROR_BOUND`).
- a **rolling window**: each histogram keeps a ring of per-interval bucket
  snapshots alongside its cumulative counts, so "p99 over the last 30s" is
  answerable — not just "p99 since construction" (which an hours-old process
  can never move again).
- :class:`LatencyDeltas` — the worker-side accumulator: process workers
  bucket observations locally and ship ``{stage: {bucket: n}}`` deltas inside
  the per-item accounting control message (the ``merge_counts`` pattern), so
  a killed worker loses only its unshipped deltas, never the history.
- :class:`PipelineLatency` — the consumer-side set of per-stage histograms
  (:data:`STAGES`), owned by ``ReaderStats`` and fed from the same timing
  sites the stage sums and tracer spans already measure.
- :class:`SLOMonitor` — declarative targets (p99 end-to-end latency, minimum
  samples/s, minimum io-overlap fraction, maximum stall episodes) with
  error-budget burn accounting: each evaluation is a pass/breach sample in a
  bounded ring, and the burn rate is the breach fraction over the allowed
  ``error_budget``. ``burn_rate >= 1`` is a **hard breach** — the budget is
  spent — and can optionally flip ``/healthz`` to 503.

Everything is **on by default** and measured within noise
(``BENCH_r14.json``); set ``PETASTORM_TPU_LATENCY=0`` to create no histogram
state at all. See ``docs/latency.md``.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

#: Environment variable gating the whole latency plane (default on).
#: ``0``/``false``/``off`` mean no histograms exist anywhere: ``ReaderStats``
#: carries ``latency=None``, workers get ``latency=False`` in their args, and
#: every record site is one attribute test.
LATENCY_ENV_VAR = 'PETASTORM_TPU_LATENCY'

#: Per-observation duration stages (seconds). ``io``/``decode`` are fed from
#: the worker's ``record_time`` sites (one observation per timed read/decode
#: section, not per item); ``queue_wait``/``deserialize`` from the consumer's
#: delivery path; ``infeed_wait``/``train_step`` from the JAX loader's
#: iteration loop; ``device_stage`` from the staging helpers; ``e2e_batch``
#: is ventilate-timestamp → batch delivery, correlated through the lineage
#: seq (see ``docs/latency.md``); ``io_range`` is one planned object-store
#: range fetch (``ParallelRangeReader.fetch_range``, hedge+retry included);
#: ``peer_fetch`` is one shared-cache peer HTTP fetch attempt (see
#: ``docs/pod_observability.md``); ``device_step``/``host_overhead`` are the
#: goodput plane's per-step decomposition of the train wall (fence time vs
#: the rest — see ``docs/goodput.md``; ``host_overhead`` records only on
#: fenced steps, where the split was actually measured).
STAGES = ('io', 'decode', 'queue_wait', 'deserialize', 'infeed_wait',
          'train_step', 'device_stage', 'e2e_batch', 'io_range',
          'peer_fetch', 'device_step', 'host_overhead')

#: ``ReaderStats`` time-stage names → latency stage fed from the same
#: ``record_time`` call (worker-side observations).
TIME_STAGE_TO_LATENCY = {'worker_io_s': 'io', 'worker_decode_s': 'decode'}

#: Geometric bucket scheme. Boundaries are **fixed module-level constants**:
#: mergeability by bucket-count addition depends on every instance (and both
#: ends of the process boundary) agreeing on them, so they are never
#: configurable per instance. Bucket ``i`` counts observations
#: ``v <= BUCKET_BOUNDS_S[i]`` (and above the previous bound); one final
#: overflow bucket catches everything beyond the last bound (``+Inf``).
BUCKET_GROWTH = 2.0 ** 0.25          # ~1.189: 4 buckets per octave
FIRST_BUCKET_BOUND_S = 1e-6          # 1 µs
NUM_BUCKETS = 136                    # covers 1 µs .. ~1.4 h before overflow
BUCKET_BOUNDS_S = tuple(FIRST_BUCKET_BOUND_S * BUCKET_GROWTH ** i
                        for i in range(NUM_BUCKETS))

#: Worst-case relative error of :meth:`LatencyHistogram.quantile` against the
#: exact sample quantile: an observation can sit anywhere inside its covering
#: bucket, whose bounds differ by :data:`BUCKET_GROWTH` (~18.9%). Tests hold
#: the estimator to this bound on known distributions.
QUANTILE_REL_ERROR_BOUND = BUCKET_GROWTH - 1.0

#: Rolling-window defaults: a ring of ``DEFAULT_WINDOW_INTERVALS`` closed
#: interval snapshots of ``DEFAULT_INTERVAL_S`` each (+ the open interval)
#: answers "p99 over the last ~30s".
DEFAULT_INTERVAL_S = 5.0
DEFAULT_WINDOW_INTERVALS = 6

_LOG_GROWTH = math.log(BUCKET_GROWTH)
_LOG_FIRST = math.log(FIRST_BUCKET_BOUND_S)

_PERCENTILES = (('p50', 0.50), ('p90', 0.90), ('p99', 0.99), ('p999', 0.999))


def latency_enabled() -> bool:
    """The :data:`LATENCY_ENV_VAR` gate (default on)."""
    value = os.environ.get(LATENCY_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def bucket_index(seconds: float) -> int:
    """Index of the bucket counting ``seconds``: the smallest ``i`` with
    ``seconds <= BUCKET_BOUNDS_S[i]``, or :data:`NUM_BUCKETS` (overflow).
    Pure arithmetic — no search — because the bounds are geometric."""
    if seconds <= FIRST_BUCKET_BOUND_S:
        return 0
    index = int(math.ceil((math.log(seconds) - _LOG_FIRST) / _LOG_GROWTH
                          - 1e-9))
    if index >= NUM_BUCKETS:
        return NUM_BUCKETS
    # float log can land one bucket low at an exact boundary; nudge up
    if seconds > BUCKET_BOUNDS_S[index]:
        index += 1
    return min(index, NUM_BUCKETS)


def bucket_lower_bound(index: int) -> float:
    """Lower bound of bucket ``index`` (0 for the first bucket)."""
    if index <= 0:
        return 0.0
    return BUCKET_BOUNDS_S[min(index, NUM_BUCKETS) - 1]


def _quantile_from_counts(counts: np.ndarray, q: float) -> Optional[float]:
    """Estimate the ``q`` quantile from a bucket-count array (length
    ``NUM_BUCKETS + 1``, overflow last). Geometric interpolation inside the
    covering bucket; ``None`` when the histogram is empty. Overflow-bucket
    hits estimate at the last finite bound (the honest floor — the true
    value is *at least* that)."""
    total = int(counts.sum())
    if total == 0:
        return None
    rank = q * total
    cum = np.cumsum(counts)
    index = int(np.searchsorted(cum, rank, side='left'))
    if index >= NUM_BUCKETS:
        return BUCKET_BOUNDS_S[-1]
    in_bucket = int(counts[index])
    before = int(cum[index]) - in_bucket
    fraction = (rank - before) / in_bucket if in_bucket else 1.0
    fraction = min(1.0, max(0.0, fraction))
    lo = bucket_lower_bound(index)
    hi = BUCKET_BOUNDS_S[index]
    if lo <= 0.0:
        return hi * fraction
    # geometric interpolation: log-uniform within the bucket matches the
    # log-bucketed scheme (linear would bias estimates toward the upper edge)
    return lo * (hi / lo) ** fraction


class LatencyHistogram:
    """Thread-safe streaming histogram over the fixed geometric buckets.

    Holds cumulative counts since construction/:meth:`reset` plus a ring of
    closed per-interval count snapshots for rolling-window quantiles. All
    mutation is a lock + integer adds — cheap enough for per-observation
    calls on the sample path.

    ``interval_s``/``window_intervals`` size the rolling window;
    ``clock`` is injectable for tests (must be monotonic)."""

    __slots__ = ('_lock', '_counts', '_sum', '_count', '_interval_s',
                 '_window_intervals', '_clock', '_interval_counts',
                 '_interval_start', '_intervals')

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 window_intervals: int = DEFAULT_WINDOW_INTERVALS,
                 clock: Callable[[], float] = time.perf_counter):
        if interval_s <= 0:
            raise ValueError('interval_s must be positive, got '
                             '{!r}'.format(interval_s))
        if window_intervals < 1:
            raise ValueError('window_intervals must be >= 1, got '
                             '{!r}'.format(window_intervals))
        self._lock = threading.Lock()
        self._interval_s = interval_s
        self._window_intervals = window_intervals
        self._clock = clock
        self._init_locked()

    def _init_locked(self) -> None:
        # plain int lists, not numpy arrays: a scalar `list[i] += 1` is ~10x
        # cheaper than a numpy indexed increment, and record() is the hot
        # path — reads (quantiles, windows, exports) convert on demand
        self._counts = [0] * (NUM_BUCKETS + 1)
        self._sum = 0.0
        self._count = 0
        self._interval_counts = [0] * (NUM_BUCKETS + 1)
        self._interval_start = self._clock()
        # ring of closed interval count lists, newest last
        self._intervals: List[List[int]] = []

    def reset(self) -> None:
        with self._lock:
            self._init_locked()

    def _maybe_roll_locked(self, now: float) -> None:
        """Close elapsed intervals into the ring (empty intervals included —
        a quiet 20s must age old spikes out of the window)."""
        elapsed = now - self._interval_start
        if elapsed < self._interval_s:
            return
        steps = int(elapsed / self._interval_s)
        # first closed interval carries the accumulated counts ...
        self._intervals.append(self._interval_counts)
        # ... any further fully-elapsed intervals were silent
        empties = min(max(0, steps - 1), self._window_intervals)
        for _ in range(empties):
            self._intervals.append([0] * (NUM_BUCKETS + 1))
        if len(self._intervals) > self._window_intervals:
            del self._intervals[:len(self._intervals)
                                - self._window_intervals]
        self._interval_counts = [0] * (NUM_BUCKETS + 1)
        self._interval_start += steps * self._interval_s

    def record(self, seconds: float) -> None:
        """Record one observation."""
        if seconds < 0.0:
            seconds = 0.0
        index = bucket_index(seconds)
        with self._lock:
            self._maybe_roll_locked(self._clock())
            self._counts[index] += 1
            self._interval_counts[index] += 1
            self._sum += seconds
            self._count += 1

    def merge_delta(self, delta: dict) -> None:
        """Merge a shipped delta (``{'buckets': {index: n}, 'sum': s,
        'count': n}`` — what :meth:`LatencyDeltas.drain` produces). Pure
        bucket-count addition: the fixed boundaries make any two histograms
        (or a histogram and a delta) mergeable."""
        if not delta:
            return
        buckets = delta.get('buckets') or {}
        with self._lock:
            self._maybe_roll_locked(self._clock())
            for index, n in buckets.items():
                index = min(int(index), NUM_BUCKETS)
                self._counts[index] += n
                self._interval_counts[index] += n
            self._sum += float(delta.get('sum', 0.0))
            self._count += int(delta.get('count', 0))

    def merge(self, other: 'LatencyHistogram') -> None:
        """Merge another histogram's cumulative counts into this one."""
        with other._lock:
            counts = list(other._counts)
            total_sum, total_count = other._sum, other._count
        with self._lock:
            self._maybe_roll_locked(self._clock())
            for index, n in enumerate(counts):
                if n:
                    self._counts[index] += n
                    self._interval_counts[index] += n
            self._sum += total_sum
            self._count += total_count

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum_s(self) -> float:
        with self._lock:
            return self._sum

    def counts(self) -> np.ndarray:
        """Copy of the cumulative bucket counts (overflow last)."""
        with self._lock:
            return np.asarray(self._counts, dtype=np.int64)

    def _window_counts_locked(self) -> np.ndarray:
        window = np.asarray(self._interval_counts, dtype=np.int64)
        for interval in self._intervals:
            window = window + np.asarray(interval, dtype=np.int64)
        return window

    def window_counts(self) -> np.ndarray:
        """Bucket counts over the rolling window (closed ring intervals plus
        the open one)."""
        with self._lock:
            self._maybe_roll_locked(self._clock())
            return self._window_counts_locked()

    def window_span_s(self) -> float:
        """The wall span the rolling window currently covers."""
        with self._lock:
            self._maybe_roll_locked(self._clock())
            now = self._clock()
            return (len(self._intervals) * self._interval_s
                    + max(0.0, now - self._interval_start))

    def quantile(self, q: float, window: bool = False) -> Optional[float]:
        """Estimated ``q`` quantile in seconds (``None`` when empty);
        ``window=True`` answers over the rolling window only."""
        if not 0.0 < q < 1.0:
            raise ValueError('q must be in (0, 1), got {!r}'.format(q))
        with self._lock:
            self._maybe_roll_locked(self._clock())
            counts = (self._window_counts_locked() if window
                      else np.asarray(self._counts, dtype=np.int64))
        return _quantile_from_counts(counts, q)

    def percentiles(self, window: bool = False) -> Dict[str, Optional[float]]:
        """``{'p50', 'p90', 'p99', 'p999'}`` in one pass."""
        with self._lock:
            self._maybe_roll_locked(self._clock())
            counts = (self._window_counts_locked() if window
                      else np.asarray(self._counts, dtype=np.int64))
        return {name: _quantile_from_counts(counts, q)
                for name, q in _PERCENTILES}

    def recent_interval_p99s(self) -> List[Optional[float]]:
        """Per-closed-interval p99 estimates, oldest first — the trend line a
        flight record embeds so a stall dump shows whether the tail blew up
        as a cliff or crept up over the whole window."""
        with self._lock:
            self._maybe_roll_locked(self._clock())
            intervals = [np.asarray(interval, dtype=np.int64)
                         for interval in self._intervals]
        return [_quantile_from_counts(interval, 0.99)
                for interval in intervals]

    def state(self) -> dict:
        """JSON-able export: nonzero ``(bucket_index, count)`` pairs plus
        ``sum``/``count`` — what Prometheus rendering and flight records
        consume (and what two processes could merge byte-for-byte)."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        return {'buckets': [[i, n] for i, n in enumerate(counts) if n],
                'sum': total_sum, 'count': total_count}


class LatencyDeltas:
    """Worker-side accumulator: buckets observations locally, drains compact
    deltas for the accounting message.

    Not locked: a worker records and drains on its own thread (the same
    single-writer discipline as ``WorkerBase.stage_times``), and the drained
    dict is immutable once shipped."""

    __slots__ = ('_stages',)

    def __init__(self):
        self._stages: Dict[str, dict] = {}

    def record(self, stage: str, seconds: float) -> None:
        if seconds < 0.0:
            seconds = 0.0
        entry = self._stages.get(stage)
        if entry is None:
            entry = self._stages[stage] = {'buckets': {}, 'sum': 0.0,
                                           'count': 0}
        index = bucket_index(seconds)
        buckets = entry['buckets']
        buckets[index] = buckets.get(index, 0) + 1
        entry['sum'] += seconds
        entry['count'] += 1

    def record_time_stage(self, stage: str, seconds: float) -> None:
        """Record against a ``ReaderStats`` time-stage name (``worker_io_s``
        → ``io``); non-latency stages are ignored."""
        mapped = TIME_STAGE_TO_LATENCY.get(stage)
        if mapped is not None:
            self.record(mapped, seconds)

    def absorb(self, deltas: Optional[Dict[str, dict]]) -> None:
        """Fold another drained ``{stage: delta}`` mapping into this
        accumulator (pure bucket-count addition). This is how a worker folds
        deltas drained from a component it owns (``ParallelRangeReader``,
        the shared cache) into its own per-message shipment — same
        single-writer discipline as :meth:`record`."""
        if not deltas:
            return
        for stage, delta in deltas.items():
            entry = self._stages.get(stage)
            if entry is None:
                entry = self._stages[stage] = {'buckets': {}, 'sum': 0.0,
                                               'count': 0}
            buckets = entry['buckets']
            for index, n in (delta.get('buckets') or {}).items():
                index = min(int(index), NUM_BUCKETS)
                buckets[index] = buckets.get(index, 0) + int(n)
            entry['sum'] += float(delta.get('sum', 0.0))
            entry['count'] += int(delta.get('count', 0))

    def drain(self) -> Optional[Dict[str, dict]]:
        """Return and reset the accumulated deltas (``None`` when empty), in
        the shape :meth:`PipelineLatency.merge_deltas` absorbs."""
        if not self._stages:
            return None
        stages, self._stages = self._stages, {}
        return stages


class PipelineLatency:
    """The consumer-side latency plane of one reader: a fixed set of
    per-stage :class:`LatencyHistogram`\\ s (:data:`STAGES`). Owned by
    ``ReaderStats`` (``stats.latency``); ``None`` there under the kill
    switch, so every feed site is a single attribute test."""

    __slots__ = ('histograms',)

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 window_intervals: int = DEFAULT_WINDOW_INTERVALS,
                 clock: Callable[[], float] = time.perf_counter):
        self.histograms: Dict[str, LatencyHistogram] = {
            stage: LatencyHistogram(interval_s=interval_s,
                                    window_intervals=window_intervals,
                                    clock=clock)
            for stage in STAGES}

    def record(self, stage: str, seconds: float) -> None:
        histogram = self.histograms.get(stage)
        if histogram is not None:
            histogram.record(seconds)

    def merge_deltas(self, deltas: Optional[Dict[str, dict]]) -> None:
        """Absorb a worker's drained ``{stage: delta}`` mapping (shipped in
        the accounting control message)."""
        if not deltas:
            return
        for stage, delta in deltas.items():
            histogram = self.histograms.get(stage)
            if histogram is not None:
                histogram.merge_delta(delta)

    def reset(self) -> None:
        for histogram in self.histograms.values():
            histogram.reset()

    def quantile(self, stage: str, q: float,
                 window: bool = False) -> Optional[float]:
        histogram = self.histograms.get(stage)
        return histogram.quantile(q, window=window) if histogram else None

    def window_p99s(self) -> Dict[str, float]:
        """Rolling-window p99 per stage, stages with window data only — the
        compact sensor view the autotune controller reads each tick (and a
        cheap answer to "what does the tail look like right now")."""
        out = {}
        for stage, histogram in self.histograms.items():
            p99 = histogram.quantile(0.99, window=True)
            if p99 is not None:
                out[stage] = p99
        return out

    def export_state(self) -> Dict[str, dict]:
        """``{stage: state}`` for stages with at least one observation —
        what rides under ``'_latency_histograms'`` in stats snapshots (and
        from there into ``/metrics`` histogram rendering and flight
        records)."""
        out = {}
        for stage, histogram in self.histograms.items():
            state = histogram.state()
            if state['count']:
                out[stage] = state
        return out

    def summary(self, window: bool = False) -> Dict[str, dict]:
        """Human-facing per-stage percentiles (stages with data only)."""
        out = {}
        for stage, histogram in self.histograms.items():
            count = histogram.count
            if not count:
                continue
            entry = {'count': count,
                     'sum_s': round(histogram.sum_s, 6)}
            for name, value in histogram.percentiles(window=window).items():
                entry[name + '_s'] = (round(value, 6)
                                      if value is not None else None)
            out[stage] = entry
        return out

    def flight_summary(self) -> dict:
        """The ``latency`` section of a flight record: lifetime + rolling
        window percentiles per stage, and the per-interval p99 trend (oldest
        first) so a stall dump distinguishes a cliff from a creep."""
        trend = {}
        for stage, histogram in self.histograms.items():
            p99s = histogram.recent_interval_p99s()
            if any(p is not None for p in p99s):
                trend[stage] = [round(p, 6) if p is not None else None
                                for p in p99s]
        return {'stages': self.summary(),
                'window': self.summary(window=True),
                'p99_trend': trend}


# -- SLO monitor --------------------------------------------------------------

#: Recognized SLO target keys (the ``slo=dict(...)`` factory knob).
SLO_TARGET_KEYS = ('p99_e2e_ms', 'p99_queue_wait_ms', 'min_samples_per_s',
                   'min_io_overlap_fraction', 'max_stall_episodes',
                   'min_goodput', 'error_budget', 'budget_window',
                   'fail_healthz', 'eval_interval_s', 'min_evaluations')

#: Fraction of evaluations allowed to breach before the budget is spent.
DEFAULT_ERROR_BUDGET = 0.01

#: Evaluation verdicts kept in the burn-accounting ring.
DEFAULT_BUDGET_WINDOW = 120

#: Minimum spacing between RECORDED burn samples. Evaluations inside the
#: interval still compute fresh checks but do not append to the ring, so the
#: burn rate is independent of how often observers look — a k8s probe every
#: 2s plus a Prometheus scrape every 5s advance the ring no faster than one
#: sample per interval (``error_budget`` keeps a fixed cadence to be a
#: budget *of*). ``eval_interval_s=0`` records every evaluation (tests).
DEFAULT_EVAL_INTERVAL_S = 5.0

#: Recorded evaluations required before ``hard_breach`` may assert: the
#: warmup grace. Without it, the FIRST evaluation of a cold pipeline (rates
#: still ramping) breaching ``min_samples_per_s`` reads as burn
#: ``1/error_budget`` and — under ``fail_healthz`` — 503s the pod into a
#: restart loop before it ever warms.
DEFAULT_MIN_EVALUATIONS = 10


def validate_slo_targets(targets: dict) -> dict:
    """Validate and normalize an ``slo=dict(...)`` knob at construction —
    a typo'd target name must fail the factory call, not silently never
    breach."""
    if not isinstance(targets, dict):
        raise ValueError('slo must be a dict of targets, got '
                         '{!r}'.format(type(targets)))
    unknown = set(targets) - set(SLO_TARGET_KEYS)
    if unknown:
        raise ValueError('unknown slo target(s) {}; valid keys: {}'.format(
            sorted(unknown), ', '.join(SLO_TARGET_KEYS)))
    out = dict(targets)
    budget = out.setdefault('error_budget', DEFAULT_ERROR_BUDGET)
    if not 0.0 < float(budget) <= 1.0:
        raise ValueError('error_budget must be in (0, 1], got '
                         '{!r}'.format(budget))
    window = out.setdefault('budget_window', DEFAULT_BUDGET_WINDOW)
    if int(window) < 1:
        raise ValueError('budget_window must be >= 1, got {!r}'.format(window))
    interval = out.setdefault('eval_interval_s', DEFAULT_EVAL_INTERVAL_S)
    if float(interval) < 0:
        raise ValueError('eval_interval_s must be >= 0, got '
                         '{!r}'.format(interval))
    min_evals = out.setdefault('min_evaluations', DEFAULT_MIN_EVALUATIONS)
    if int(min_evals) < 1:
        raise ValueError('min_evaluations must be >= 1, got '
                         '{!r}'.format(min_evals))
    out.setdefault('fail_healthz', False)
    for key in ('p99_e2e_ms', 'p99_queue_wait_ms', 'min_samples_per_s',
                'min_io_overlap_fraction', 'max_stall_episodes'):
        value = out.get(key)
        if value is not None and float(value) < 0:
            raise ValueError('{} must be >= 0, got {!r}'.format(key, value))
    goodput = out.get('min_goodput')
    if goodput is not None and not 0.0 <= float(goodput) <= 1.0:
        raise ValueError('min_goodput is a fraction in [0, 1], got '
                         '{!r}'.format(goodput))
    return out


class SLOMonitor:
    """Declarative SLO targets over the latency plane + stats snapshot, with
    error-budget burn accounting.

    Each :meth:`evaluate` compares the current rolling-window state against
    the targets; at most one pass/breach sample per ``eval_interval_s`` is
    RECORDED into a bounded ring (observers — ``/healthz`` probes, ``/slo``
    scrapes, ``/diagnostics`` — evaluate freely without advancing the burn
    accounting faster than the cadence, so the budget is probe-rate
    independent). The **burn rate** is the ring's breach fraction divided by
    the allowed ``error_budget`` (burn 1.0 = the budget is exactly spent;
    2.0 = breaching twice as often as allowed). ``hard_breach`` (burn >= 1,
    after at least ``min_evaluations`` recorded samples — the warmup grace)
    optionally flips ``/healthz`` to 503 when the ``fail_healthz`` target is
    set — the k8s hook for "this infeed is violating its SLO, recycle it".

    The watchdog thread drives periodic evaluations when armed
    (``stall_timeout=``); ``/slo`` and flight records evaluate on demand.

    Latency-based targets need the latency plane: under the kill switch (or
    before any observation) they report ``measured: None`` and **skip**
    rather than silently pass — the verdict carries ``skipped_checks`` so a
    disabled sensor is never mistaken for a green one.
    """

    def __init__(self, targets: dict,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 latency: Optional[PipelineLatency] = None):
        self.targets = validate_slo_targets(targets)
        self._snapshot_fn = snapshot_fn
        self._latency = latency
        self._lock = threading.Lock()
        self._verdict_ring: List[bool] = []   # True = breached
        self._last_record_ts: Optional[float] = None
        self._stall_episodes = 0
        self.last_verdict: Optional[dict] = None

    @property
    def fail_healthz(self) -> bool:
        return bool(self.targets.get('fail_healthz'))

    def record_stall_episode(self) -> None:
        """Count one watchdog stall episode (edge-triggered upstream)."""
        with self._lock:
            self._stall_episodes += 1

    def _check_latency(self, checks, skipped, key, stage):
        target_ms = self.targets.get(key)
        if target_ms is None:
            return False
        measured = (self._latency.quantile(stage, 0.99, window=True)
                    if self._latency is not None else None)
        if measured is None:
            # no sensor (kill switch) or no data yet: skip, loudly
            checks[key] = {'target_ms': float(target_ms), 'measured_ms': None,
                           'ok': None}
            skipped.append(key)
            return False
        measured_ms = measured * 1000.0
        ok = measured_ms <= float(target_ms)
        checks[key] = {'target_ms': float(target_ms),
                       'measured_ms': round(measured_ms, 3), 'ok': ok}
        return not ok

    def evaluate(self, snapshot: Optional[dict] = None) -> dict:
        """One SLO evaluation: per-target verdicts, the breach list, and the
        updated burn accounting. JSON-able."""
        if snapshot is None and self._snapshot_fn is not None:
            snapshot = self._snapshot_fn()
        snapshot = snapshot or {}
        checks: Dict[str, dict] = {}
        skipped: List[str] = []
        breached = False

        breached |= self._check_latency(checks, skipped, 'p99_e2e_ms',
                                        'e2e_batch')
        breached |= self._check_latency(checks, skipped, 'p99_queue_wait_ms',
                                        'queue_wait')

        target = self.targets.get('min_samples_per_s')
        if target is not None:
            measured = snapshot.get('items_per_s')
            ok = measured is not None and measured >= float(target)
            checks['min_samples_per_s'] = {
                'target': float(target),
                'measured': round(measured, 3) if measured is not None
                else None,
                'ok': ok}
            breached |= not ok

        target = self.targets.get('min_io_overlap_fraction')
        if target is not None:
            measured = snapshot.get('io_overlap_fraction')
            ok = measured is not None and measured >= float(target)
            checks['min_io_overlap_fraction'] = {
                'target': float(target),
                'measured': round(measured, 4) if measured is not None
                else None,
                'ok': ok}
            breached |= not ok

        target = self.targets.get('min_goodput')
        if target is not None:
            # derived by ReaderStats.snapshot() once the goodput plane has
            # closed a step; None (plane kill-switched, or no loader steps
            # yet) skips loudly — same contract as the latency checks
            measured = snapshot.get('goodput_fraction')
            if measured is None:
                checks['min_goodput'] = {'target': float(target),
                                         'measured': None, 'ok': None}
                skipped.append('min_goodput')
            else:
                ok = measured >= float(target)
                checks['min_goodput'] = {'target': float(target),
                                         'measured': round(measured, 4),
                                         'ok': ok}
                breached |= not ok

        target = self.targets.get('max_stall_episodes')
        if target is not None:
            with self._lock:
                episodes = self._stall_episodes
            ok = episodes <= int(target)
            checks['max_stall_episodes'] = {'target': int(target),
                                            'measured': episodes, 'ok': ok}
            breached |= not ok

        budget = float(self.targets['error_budget'])
        window = int(self.targets['budget_window'])
        interval = float(self.targets['eval_interval_s'])
        min_evaluations = int(self.targets['min_evaluations'])
        now = time.perf_counter()
        with self._lock:
            # record at most one burn sample per interval: probe/scrape
            # frequency must not be able to flush (or multiply) breach
            # samples — the budget's cadence belongs to the monitor
            if (self._last_record_ts is None
                    or now - self._last_record_ts >= interval):
                self._last_record_ts = now
                self._verdict_ring.append(bool(breached))
                if len(self._verdict_ring) > window:
                    del self._verdict_ring[:len(self._verdict_ring) - window]
            evaluations = len(self._verdict_ring)
            breaches = sum(self._verdict_ring)
            episodes = self._stall_episodes
        breach_fraction = breaches / evaluations if evaluations else 0.0
        burn_rate = breach_fraction / budget if budget else 0.0
        verdict = {
            'targets': {k: v for k, v in self.targets.items()
                        if v is not None},
            'checks': checks,
            'breached': bool(breached),
            'breached_checks': sorted(k for k, c in checks.items()
                                      if c['ok'] is False),
            'skipped_checks': skipped,
            'stall_episodes': episodes,
            'evaluations': evaluations,
            'breached_evaluations': breaches,
            'error_budget': budget,
            'budget_window': window,
            'breach_fraction': round(breach_fraction, 4),
            'burn_rate': round(burn_rate, 4),
            # warmup grace: one cold-start breach must not read as a spent
            # budget (1/error_budget) and recycle the pod before it warms
            'hard_breach': (burn_rate >= 1.0
                            and evaluations >= min_evaluations),
            'min_evaluations': min_evaluations,
            'fail_healthz': self.fail_healthz,
        }
        self.last_verdict = verdict
        return verdict


def prometheus_histogram_lines(name: str, state: dict,
                               help_text: str = '') -> List[str]:
    """Render one histogram ``state`` (:meth:`LatencyHistogram.state`) in
    Prometheus text-exposition **histogram** form: cumulative ``_bucket``
    samples with ``le`` labels, the mandatory terminal ``le="+Inf"`` bucket,
    and ``_sum``/``_count``. Only buckets with observations are emitted
    (cumulative semantics make sparse ``le`` sets valid), keeping scrapes
    proportional to occupied buckets, not the 137-bucket scheme."""
    lines = []
    if help_text:
        lines.append('# HELP {} {}'.format(name, help_text))
    lines.append('# TYPE {} histogram'.format(name))
    cumulative = 0
    for index, count in state.get('buckets', ()):
        cumulative += count
        if index >= NUM_BUCKETS:
            break   # overflow folds into the +Inf terminal bucket
        lines.append('{}_bucket{{le="{:.9g}"}} {}'.format(
            name, BUCKET_BOUNDS_S[index], cumulative))
    lines.append('{}_bucket{{le="+Inf"}} {}'.format(name,
                                                    int(state.get('count',
                                                                  0))))
    lines.append('{}_sum {}'.format(name, repr(float(state.get('sum', 0.0)))))
    lines.append('{}_count {}'.format(name, int(state.get('count', 0))))
    return lines
