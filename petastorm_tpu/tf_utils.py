"""TensorFlow adapter (reference parity: ``petastorm/tf_utils.py``).

Provides ``make_petastorm_dataset(reader)`` → ``tf.data.Dataset`` via
``from_generator`` with static-shape fixup, the dtype/value sanitization
table (uint16→int32, uint32→int64, Decimal→string, datetime64→int64 ns), and
the graph-mode ``tf_tensors`` API (py_func + optional RandomShuffleQueue,
reference ``tf_utils.py:270-327``) for TF1-compat session code — new code
should prefer ``tf.data``.

TensorFlow is imported lazily so the rest of the framework never pays for it.
"""

from __future__ import annotations

import datetime
from decimal import Decimal

import numpy as np


def _tf():
    import tensorflow as tf
    return tf


def _field_tf_dtype(field):
    """numpy dtype -> tf dtype incl. promotions (reference ``tf_utils.py:27-44``):
    uint16→int32, uint32→int64, Decimal→string, datetime→int64 ns."""
    tf = _tf()
    np_dtype = field.numpy_dtype
    if np_dtype in (str, bytes, Decimal, np.str_, np.bytes_):
        return tf.string
    if np_dtype in (np.datetime64, datetime.date, datetime.datetime):
        return tf.int64
    dt = np.dtype(np_dtype)
    if dt == np.uint16:
        return tf.int32
    if dt == np.uint32:
        return tf.int64
    if dt.kind == 'M':
        return tf.int64
    return tf.as_dtype(dt)


def _sanitize_field_tf_types(value):
    """Make one field value feedable to TF (reference ``tf_utils.py:58-97``)."""
    if value is None:
        raise RuntimeError('Null values are not supported by the TF adapter; '
                           'use a TransformSpec to fill nulls')
    if isinstance(value, Decimal):
        return str(value)
    arr = np.asarray(value)
    if arr.dtype.kind == 'M':
        return arr.astype('datetime64[ns]').astype(np.int64)
    if arr.dtype == np.uint16:
        return arr.astype(np.int32)
    if arr.dtype == np.uint32:
        return arr.astype(np.int64)
    if arr.dtype.kind == 'O':
        if arr.size and isinstance(arr.flat[0], Decimal):
            return arr.astype(str)
        return arr.astype(str) if arr.size and isinstance(arr.flat[0], str) else arr
    return arr


def _sanitize_row(row_dict):
    return {k: _sanitize_field_tf_types(v) for k, v in row_dict.items()}


def make_petastorm_dataset(reader):
    """Build a ``tf.data.Dataset`` over a row or batch reader
    (reference ``tf_utils.py:329-399``).

    Elements are namedtuples of tensors (one row each for ``make_reader``, one
    row-group batch each for ``make_batch_reader``; apply
    ``.unbatch()``/``.flat_map`` + ``.batch()`` for fixed-size training
    batches). The dataset is single-pass per reader epoch set: use
    ``num_epochs=None`` in the reader instead of ``.repeat()``
    (reference refuses re-iteration the same way, ``tf_utils.py:366-374``).
    """
    tf = _tf()
    schema = reader.schema
    if getattr(reader, 'ngram', None) is not None:
        return _make_ngram_dataset(reader)

    fields = list(schema.fields.values())
    names = [f.name for f in fields]
    output_types = tuple(_field_tf_dtype(f) for f in fields)

    def generator():
        for item in reader:
            row = item._asdict() if hasattr(item, '_asdict') else dict(item)
            sane = _sanitize_row(row)
            yield tuple(sane[n] for n in names)

    dataset = tf.data.Dataset.from_generator(generator, output_types)

    batched = reader.batched_output

    def set_shape_and_name(*row):
        out = [_set_static_shape(value, field, batched)
               for value, field in zip(row, fields)]
        # namedtuple row type with tensor values (same type the raw reader
        # yields for decoded rows)
        return schema.make_batch_namedtuple(**dict(zip(names, out)))

    return dataset.map(set_shape_and_name)


def _make_ngram_dataset(reader):
    """NGram rows are {offset: namedtuple}; flatten across the generator
    boundary and rebuild the dict of namedtuples (reference
    ``tf_utils.py:141-183,402-433``)."""
    tf = _tf()
    ngram = reader.ngram
    timesteps = sorted(ngram.fields.keys())
    flat_fields = []
    for ts in timesteps:
        schema_at_ts = ngram.get_schema_at_timestep(reader.schema, ts)
        for f in schema_at_ts.fields.values():
            flat_fields.append((ts, f))
    output_types = tuple(_field_tf_dtype(f) for _, f in flat_fields)

    def generator():
        for item in reader:
            out = []
            for ts, f in flat_fields:
                value = getattr(item[ts], f.name)
                out.append(_sanitize_field_tf_types(value))
            yield tuple(out)

    dataset = tf.data.Dataset.from_generator(generator, output_types)

    def unflatten(*flat):
        result = {}
        idx = 0
        for ts in timesteps:
            schema_at_ts = ngram.get_schema_at_timestep(reader.schema, ts)
            names = list(schema_at_ts.fields.keys())
            result[ts] = dict(zip(names, flat[idx:idx + len(names)]))
            idx += len(names)
        return result

    return dataset.map(unflatten)


def _set_static_shape(tensor, field, batched):
    shape = tuple(field.shape or ())
    static = tuple(s if s is not None else None for s in shape)
    if batched:
        static = (None,) + static
    try:
        tensor.set_shape(static)
    except ValueError:
        pass  # ragged/opaque: leave dynamic
    return tensor


def _maybe_shuffle_queue(tensors, dtypes, capacity, min_after_dequeue):
    """Normalize py_func output to a list and optionally route it through a
    RandomShuffleQueue, exposing the named ``random_shuffling_queue_size``
    monitoring op (reference ``tf_utils.py:46-48,208-210``)."""
    tf = _tf()
    v1 = tf.compat.v1
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]       # single-dtype py_func returns a bare tensor
    if capacity > 0:
        queue = tf.queue.RandomShuffleQueue(
            capacity, min_after_dequeue, dtypes,
            name='petastorm_tpu_shuffling_queue')
        v1.train.add_queue_runner(
            v1.train.QueueRunner(queue, [queue.enqueue(tensors)]))
        v1.identity(tf.cast(queue.size(), tf.int32),
                    name='random_shuffling_queue_size')
        tensors = queue.dequeue()
        if not isinstance(tensors, (list, tuple)):
            tensors = [tensors]   # single-component dequeue, same deal
    return tensors


def tf_tensors(reader, shuffling_queue_capacity=0, min_after_dequeue=0):
    """Graph-mode tensors: each ``session.run`` pulls the next row (or
    row-group batch) from the reader (reference ``tf_utils.py:270-327``; queue
    variant ``:202-252``).

    TF1-compat API for legacy graph/session code — build under
    ``tf.compat.v1.Graph`` and evaluate with a ``tf.compat.v1.Session``.
    Reader exhaustion surfaces as ``tf.errors.OutOfRangeError``, the standard
    end-of-input signal graph training loops already handle. With
    ``shuffling_queue_capacity > 0`` rows pass through a
    ``RandomShuffleQueue`` (start it with
    ``tf.compat.v1.train.start_queue_runners``); the queue is refused for
    batched readers exactly as the reference refuses it
    (``tf_utils.py:308-312``). New TF2 code should prefer
    :func:`make_petastorm_dataset`.
    """
    tf = _tf()
    v1 = tf.compat.v1
    schema = reader.schema
    batched = bool(getattr(reader, 'batched_output', False))
    if batched and shuffling_queue_capacity > 0:
        raise ValueError('shuffling_queue_capacity is not supported with '
                         'batched readers (reference tf_utils.py:308-312); '
                         'shuffle in the reader instead')
    ngram = getattr(reader, 'ngram', None)
    if ngram is not None:
        return _tf_tensors_ngram(reader, shuffling_queue_capacity,
                                 min_after_dequeue)

    fields = list(schema.fields.values())
    names = [f.name for f in fields]
    dtypes = [_field_tf_dtype(f) for f in fields]

    def next_row():
        # StopIteration propagates: py_func surfaces it to session.run as
        # tf.errors.OutOfRangeError, the standard end-of-input signal
        item = next(reader)
        row = item._asdict() if hasattr(item, '_asdict') else dict(item)
        sane = _sanitize_row(row)
        return [np.asarray(sane[n]) for n in names]

    tensors = v1.py_func(next_row, [], dtypes, name='petastorm_tpu_row')
    tensors = _maybe_shuffle_queue(tensors, dtypes, shuffling_queue_capacity,
                                   min_after_dequeue)
    out = [_set_static_shape(t, f, batched) for t, f in zip(tensors, fields)]
    make = schema.make_batch_namedtuple if batched else schema.make_namedtuple
    return make(**dict(zip(names, out)))


def _tf_tensors_ngram(reader, shuffling_queue_capacity, min_after_dequeue):
    """NGram variant: windows flattened across the py_func boundary and
    rebuilt as {offset: namedtuple} of tensors (reference
    ``tf_utils.py:255-267,402-433``)."""
    tf = _tf()
    v1 = tf.compat.v1
    ngram = reader.ngram
    timesteps = sorted(ngram.fields.keys())
    flat_fields = []
    for ts in timesteps:
        schema_at_ts = ngram.get_schema_at_timestep(reader.schema, ts)
        for f in schema_at_ts.fields.values():
            flat_fields.append((ts, f))
    dtypes = [_field_tf_dtype(f) for _, f in flat_fields]

    def next_window():
        item = next(reader)   # StopIteration -> OutOfRangeError via py_func
        return [np.asarray(_sanitize_field_tf_types(getattr(item[ts], f.name)))
                for ts, f in flat_fields]

    tensors = v1.py_func(next_window, [], dtypes, name='petastorm_tpu_ngram')
    tensors = _maybe_shuffle_queue(tensors, dtypes, shuffling_queue_capacity,
                                   min_after_dequeue)
    result = {}
    idx = 0
    for ts in timesteps:
        view = ngram.get_schema_at_timestep(reader.schema, ts)
        names = list(view.fields.keys())
        step = [_set_static_shape(t, f, False)
                for t, f in zip(tensors[idx:idx + len(names)],
                                view.fields.values())]
        result[ts] = view.make_namedtuple(**dict(zip(names, step)))
        idx += len(names)
    return result
