"""Unischema: a single schema definition rendered as numpy dtypes, arrow schemas
and stable row namedtuples.

Reference parity: ``petastorm/unischema.py`` — ``UnischemaField`` (:50-69),
``Unischema``/views/regex matching (:174-464), row encoding ``dict_to_spark_row``
(:359-406), ``insert_explicit_nulls`` (:409), arrow inference
``from_arrow_schema`` (:302-353) and ``_numpy_and_codec_from_arrow_type``
(:467-502).

Deviations (deliberate, TPU-first):
 - Schemas serialize to **JSON**, not pickle — no codec-class ABI trap.
 - Row encoding targets **pyarrow** storage types directly (``encode_row`` +
   ``as_arrow_schema``); there is no Spark StructType path (Spark interop, when
   needed, goes through arrow).
"""

from __future__ import annotations

import json
import re
import threading
from collections import namedtuple
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (DataframeColumnCodec, CompressedImageCodec, NdarrayCodec,
                                  ScalarCodec, codec_from_json_dict)

# Stateless default for codec-less fields; shared to keep encode_row allocation-free.
_DEFAULT_SCALAR_CODEC = ScalarCodec()


class UnischemaField:
    """A single typed field: ``(name, numpy_dtype, shape, codec, nullable)``.

    ``shape`` is a tuple where ``None`` entries are wildcards (variable
    dimensions), matching the reference semantics (``unischema.py:50-69``).
    ``codec=None`` means the value is stored natively (scalar columns in foreign
    parquet stores).
    """

    __slots__ = ('name', 'numpy_dtype', 'shape', 'codec', 'nullable')

    def __init__(self, name: str, numpy_dtype, shape: Tuple = (),
                 codec: Optional[DataframeColumnCodec] = None, nullable: bool = False):
        self.name = name
        if isinstance(numpy_dtype, type) and issubclass(numpy_dtype, (str, bytes, np.str_,
                                                                      np.bytes_)):
            # str/bytes (and numpy subclasses) are sentinel types for variable-length
            # string/binary columns — normalize to the plain python types.
            self.numpy_dtype = str if issubclass(numpy_dtype, (str, np.str_)) else bytes
        else:
            self.numpy_dtype = np.dtype(numpy_dtype)
        self.shape = tuple(shape)
        self.codec = codec
        self.nullable = bool(nullable)

    def _key(self):
        dtype_key = self.numpy_dtype if isinstance(self.numpy_dtype, type) \
            else self.numpy_dtype.str
        return (self.name, dtype_key, self.shape, self.codec, self.nullable)

    def __eq__(self, other):
        return isinstance(other, UnischemaField) and self._key() == other._key()

    def __hash__(self):
        return hash((self.name, self.shape, self.nullable))

    def __repr__(self):
        return 'UnischemaField({!r}, {}, {}, {}, nullable={})'.format(
            self.name, self.numpy_dtype, self.shape, self.codec, self.nullable)

    # -- JSON (de)serialization -------------------------------------------------

    def to_json_dict(self) -> Dict[str, Any]:
        if isinstance(self.numpy_dtype, type):  # str / bytes sentinel types
            dtype_repr = {'py': self.numpy_dtype.__name__}
        else:
            dtype_repr = {'np': self.numpy_dtype.str}
        return {
            'name': self.name,
            'dtype': dtype_repr,
            'shape': [s if s is not None else -1 for s in self.shape],
            'codec': self.codec.to_json_dict() if self.codec is not None else None,
            'nullable': self.nullable,
        }

    @classmethod
    def from_json_dict(cls, d: Dict[str, Any]) -> 'UnischemaField':
        dtype_repr = d['dtype']
        if 'py' in dtype_repr:
            dtype = {'str': str, 'bytes': bytes}[dtype_repr['py']]
        else:
            dtype = np.dtype(dtype_repr['np'])
        shape = tuple(s if s >= 0 else None for s in d['shape'])
        codec = codec_from_json_dict(d['codec']) if d.get('codec') else None
        return cls(d['name'], dtype, shape, codec, d.get('nullable', False))


class _NamedtupleCache:
    """Returns the same namedtuple type for identical (name, field-names) pairs,
    so row-type identity is stable across calls (reference ``unischema.py:88-111``).

    Thread-safe: multiple consumer threads may drain one reader concurrently,
    and without the lock two first-comers could each build their own class —
    rows of one schema would then carry different types, breaking the
    type-identity guarantee."""

    _store: Dict[str, Any] = {}
    _lock = threading.Lock()

    @classmethod
    def get(cls, parent_name: str, field_names: Iterable[str]):
        sorted_names = list(sorted(field_names))
        key = ' '.join([parent_name] + sorted_names)
        with cls._lock:
            cached = cls._store.get(key)
            if cached is None:
                cached = cls._store[key] = namedtuple(parent_name, sorted_names)
        return cached


class Unischema:
    """An ordered collection of :class:`UnischemaField` with view/regex support."""

    def __init__(self, name: str, fields: List[UnischemaField]):
        self._name = name
        self._fields = {f.name: f for f in sorted(fields, key=lambda t: t.name)}
        for f in self._fields.values():
            setattr(self, f.name, f)

    @property
    def fields(self) -> Dict[str, UnischemaField]:
        return self._fields

    def __repr__(self):
        fields_repr = ',\n  '.join(repr(f) for f in self._fields.values())
        return 'Unischema({}, [\n  {}\n])'.format(self._name, fields_repr)

    # -- views ------------------------------------------------------------------

    def create_schema_view(self, fields) -> 'Unischema':
        """Sub-schema from a list of ``UnischemaField`` instances and/or regex
        pattern strings (reference ``unischema.py:199-240``)."""
        regexes = [f for f in fields if isinstance(f, str)]
        field_objs = [f for f in fields if isinstance(f, UnischemaField)]
        for f in field_objs:
            if f.name not in self._fields or self._fields[f.name] != f:
                raise ValueError('field {} does not belong to the schema {}'.format(f, self._name))
        matched = match_unischema_fields(self, regexes) if regexes else []
        view_fields = {f.name: f for f in list(field_objs) + list(matched)}
        return Unischema('{}_view'.format(self._name), list(view_fields.values()))

    # -- row types --------------------------------------------------------------

    def _get_namedtuple(self):
        return _NamedtupleCache.get(self._name, self._fields.keys())

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple, casting string values for string-typed fields
        (reference ``unischema.py:276-292``)."""
        typed = {}
        for key, value in kwargs.items():
            field = self._fields[key]
            is_scalar_str = field.shape == () and (
                field.numpy_dtype is str
                or (not isinstance(field.numpy_dtype, type) and field.numpy_dtype.kind == 'U'))
            if value is None:
                typed[key] = None
            elif is_scalar_str and not isinstance(value, str):
                typed[key] = str(value)
            else:
                typed[key] = value
        return self._get_namedtuple()(**typed)

    def make_batch_namedtuple(self, **column_arrays):
        """Row-batch namedtuple: values are whole column arrays, no per-value
        coercion (used by the batch reader path)."""
        return self._get_namedtuple()(**column_arrays)

    # -- arrow schema / storage -------------------------------------------------

    def as_arrow_schema(self) -> pa.Schema:
        """Storage schema for the parquet files: codec-directed arrow types."""
        pa_fields = []
        for f in self._fields.values():
            codec = f.codec if f.codec is not None else _DEFAULT_SCALAR_CODEC
            pa_fields.append(pa.field(f.name, codec.arrow_type(f), nullable=f.nullable))
        return pa.schema(pa_fields)

    # -- JSON (de)serialization -------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            'name': self._name,
            'fields': [f.to_json_dict() for f in self._fields.values()],
        })

    @classmethod
    def from_json(cls, payload: str) -> 'Unischema':
        d = json.loads(payload)
        return cls(d['name'], [UnischemaField.from_json_dict(fd) for fd in d['fields']])

    # -- inference from foreign parquet ----------------------------------------

    @classmethod
    def from_arrow_schema(cls, arrow_schema: pa.Schema, omit_unsupported_fields: bool = True,
                          name: str = 'inferred_schema') -> 'Unischema':
        """Infer a Unischema for a foreign (non-petastorm) parquet store
        (reference ``unischema.py:302-353``)."""
        fields = []
        for column in arrow_schema:
            try:
                numpy_dtype, shape, codec = _numpy_and_codec_from_arrow_type(column.type)
            except ValueError:
                if omit_unsupported_fields:
                    continue
                raise
            fields.append(UnischemaField(column.name, numpy_dtype, shape, codec,
                                         nullable=column.nullable))
        return cls(name, fields)


def _numpy_and_codec_from_arrow_type(arrow_type: pa.DataType):
    """arrow type -> (numpy dtype, shape, codec) (reference ``unischema.py:467-502``)."""
    import pyarrow.types as pat
    if pat.is_int8(arrow_type):
        return np.int8, (), None
    if pat.is_uint8(arrow_type):
        return np.uint8, (), None
    if pat.is_int16(arrow_type):
        return np.int16, (), None
    if pat.is_uint16(arrow_type):
        return np.uint16, (), None
    if pat.is_int32(arrow_type):
        return np.int32, (), None
    if pat.is_uint32(arrow_type):
        return np.uint32, (), None
    if pat.is_int64(arrow_type):
        return np.int64, (), None
    if pat.is_uint64(arrow_type):
        return np.uint64, (), None
    if pat.is_float16(arrow_type):
        return np.float16, (), None
    if pat.is_float32(arrow_type):
        return np.float32, (), None
    if pat.is_float64(arrow_type):
        return np.float64, (), None
    if pat.is_boolean(arrow_type):
        return np.bool_, (), None
    if pat.is_string(arrow_type) or pat.is_large_string(arrow_type):
        return str, (), None
    if pat.is_binary(arrow_type) or pat.is_large_binary(arrow_type):
        return bytes, (), None
    if pat.is_decimal(arrow_type):
        return np.object_, (), None
    if pat.is_date(arrow_type) or pat.is_timestamp(arrow_type):
        return np.datetime64, (), None
    if pat.is_list(arrow_type) or pat.is_large_list(arrow_type):
        inner_dtype, _, _ = _numpy_and_codec_from_arrow_type(arrow_type.value_type)
        return inner_dtype, (None,), None
    if pat.is_dictionary(arrow_type):
        return _numpy_and_codec_from_arrow_type(arrow_type.value_type)
    raise ValueError('Cannot auto-create unischema field for arrow type {}'.format(arrow_type))


def match_unischema_fields(schema: Unischema, field_regexes: Iterable[str]) -> List[UnischemaField]:
    """Return fields whose names fully match any of the regex patterns
    (full-match semantics, reference ``unischema.py:437-464``)."""
    if not field_regexes:
        return []
    compiled = [re.compile(p) for p in field_regexes]
    return [f for name, f in schema.fields.items()
            if any(c.fullmatch(name) for c in compiled)]


def insert_explicit_nulls(schema: Unischema, row_dict: Dict[str, Any]) -> None:
    """Insert ``None`` for missing nullable fields; raise for missing
    non-nullable ones (reference ``unischema.py:409-434``)."""
    for name, field in schema.fields.items():
        if name not in row_dict:
            if field.nullable:
                row_dict[name] = None
            else:
                raise ValueError('Field {!r} is not found in the row and is not nullable'
                                 .format(name))


def encode_row(schema: Unischema, row_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Codec-encode one row dict into arrow-storable cell values.

    TPU-native replacement for ``dict_to_spark_row`` (reference
    ``unischema.py:359-406``): the output feeds ``pa.Table.from_pylist`` +
    ``pq.write_table`` instead of a Spark ``Row``.
    """
    if not isinstance(row_dict, dict):
        raise TypeError('row must be a dict, got {}'.format(type(row_dict)))
    row = dict(row_dict)
    extra = set(row.keys()) - set(schema.fields.keys())
    if extra:
        raise ValueError('Following fields of row are not part of the schema: {}'.format(extra))
    insert_explicit_nulls(schema, row)
    encoded = {}
    for name, field in schema.fields.items():
        value = row[name]
        if value is None:
            if not field.nullable:
                raise ValueError('Field {!r} is not nullable but got None'.format(name))
            encoded[name] = None
        else:
            codec = field.codec if field.codec is not None else _DEFAULT_SCALAR_CODEC
            encoded[name] = codec.encode(field, value)
    return encoded


def decode_row(row: Dict[str, Any], schema: Unischema,
               decode_overrides: Dict[str, Any] = None) -> Dict[str, Any]:
    """Decode one storage-form row dict using the schema's codecs
    (reference ``petastorm/utils.py:52-85``).

    ``decode_overrides`` maps field name -> callable(value) replacing the
    codec's plain ``decode`` (e.g. scaled image decode)."""
    decoded = {}
    for name, value in row.items():
        field = schema.fields.get(name)
        if field is None:
            continue
        if value is None:
            decoded[name] = None
        elif decode_overrides and name in decode_overrides:
            decoded[name] = decode_overrides[name](value)
        elif field.codec is not None:
            decoded[name] = field.codec.decode(field, value)
        elif isinstance(field.numpy_dtype, np.dtype) and field.numpy_dtype.kind in 'biufc':
            decoded[name] = field.numpy_dtype.type(value)
        else:
            decoded[name] = value
    return decoded
