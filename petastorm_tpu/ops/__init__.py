"""TPU compute kernels: Pallas implementations for the hot ops with pure-jnp
fallbacks that run anywhere (CPU meshes, interpret mode)."""

from petastorm_tpu.ops.attention import blockwise_attention, flash_attention
from petastorm_tpu.ops.normalize import normalize_images

__all__ = ['flash_attention', 'blockwise_attention', 'normalize_images']
