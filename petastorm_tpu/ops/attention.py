"""Attention kernels.

``flash_attention`` dispatches to a Pallas TPU kernel (online-softmax, never
materializes the (L, L) score matrix in HBM) and falls back to a
``lax.scan``-based blockwise jnp implementation on other backends. Both share
the same math, so tests can assert the Pallas path against the fallback.

The blockwise core is also the per-step building block of ring attention
(``petastorm_tpu/parallel/ring.py``): one (q-chunk, kv-chunk) partial update of
the running (o, m, l) accumulators.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise jnp core
# ---------------------------------------------------------------------------

def _block_update(q, k, v, o, m, l, scale, mask):
    """One online-softmax update: attend q against (k, v) and fold into the
    running (o, m, l) accumulators. Shapes: q (..., Lq, D), k/v (..., Lk, D),
    o (..., Lq, D), m/l (..., Lq)."""
    s = jnp.einsum('...qd,...kd->...qk', q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(-inf - (-inf)) -> exp(0); zero them via l
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum('...qk,...kd->...qd', p, v)
    return o_new, m_new, l_new


def attention_accumulators(q_len: int, head_dim: int, batch_shape=()):
    """Fresh (o, m, l) accumulators for online-softmax accumulation."""
    o = jnp.zeros(batch_shape + (q_len, head_dim), dtype=jnp.float32)
    m = jnp.full(batch_shape + (q_len,), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros(batch_shape + (q_len,), dtype=jnp.float32)
    return o, m, l


def finalize_attention(o, l):
    """Normalize accumulated output; fully-masked rows yield zeros."""
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return o / safe_l[..., None]


def attention_block_step(q, k, v, o, m, l, *, scale=None,
                         q_positions=None, k_positions=None, causal=True):
    """Public building block used by ring attention: fold one kv chunk into the
    accumulators, masking by absolute token positions when ``causal``."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mask = None
    if causal:
        if q_positions is None or k_positions is None:
            raise ValueError('causal masking needs q_positions/k_positions')
        mask = q_positions[..., :, None] >= k_positions[..., None, :]
    return _block_update(q, k, v, o, m, l, scale, mask)


def blockwise_attention(q, k, v, *, causal: bool = True, block_k: int = 512):
    """Memory-efficient attention: scan over key/value blocks with online
    softmax. Works on any backend; O(L·block_k) live memory per head.

    Shapes: q/k/v ``(..., L, D)``; returns ``(..., L, D)`` in q's dtype.
    """
    orig_dtype = q.dtype
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / math.sqrt(q.shape[-1])
    q_len, k_len = q.shape[-2], k.shape[-2]
    batch_shape = q.shape[:-2]

    pad = (-k_len) % block_k
    if pad:
        pad_width = [(0, 0)] * (k32.ndim - 2) + [(0, pad), (0, 0)]
        k32 = jnp.pad(k32, pad_width)
        v32 = jnp.pad(v32, pad_width)
    padded_k_len = k_len + pad
    num_blocks = padded_k_len // block_k

    # (num_blocks, ..., block_k, D) for scanning
    def to_blocks(x):
        x = jnp.moveaxis(x, -2, 0)                     # (Lk, ..., D)
        x = x.reshape((num_blocks, block_k) + x.shape[1:])
        return jnp.moveaxis(x, 1, -2)                  # (nb, ..., block_k, D)

    kb, vb = to_blocks(k32), to_blocks(v32)
    q_pos = jnp.arange(q_len)
    o, m, l = attention_accumulators(q_len, q.shape[-1], batch_shape)

    def step(carry, inputs):
        o, m, l = carry
        k_blk, v_blk, blk_idx = inputs
        k_pos = blk_idx * block_k + jnp.arange(block_k)
        valid = k_pos < k_len                           # mask tail padding
        if causal:
            mask = (q_pos[:, None] >= k_pos[None, :]) & valid[None, :]
        else:
            mask = jnp.broadcast_to(valid[None, :], (q_len, block_k))
        o, m, l = _block_update(q32, k_blk, v_blk, o, m, l, scale, mask)
        return (o, m, l), None

    (o, m, l), _ = jax.lax.scan(step, (o, m, l),
                                (kb, vb, jnp.arange(num_blocks)))
    return finalize_attention(o, l).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_seq_len: int, kv_seq_len: int, block_q: int):
    """One (batch·head, q-block) program: scan kv blocks held in VMEM.

    Block shapes: q_ref (block_q, D), k_ref/v_ref (kv_seq_len, D) — the kernel
    slices kv blocks itself so the MXU sees (block_q, D) x (D, block_k) matmuls.
    """
    from jax.experimental import pallas as pl

    q_blk_idx = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    q_pos = q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0).squeeze(-1)

    num_kv_blocks = kv_seq_len // block_k

    def body(kv_idx, carry):
        o, m, l = carry
        k = k_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kv_idx * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1).squeeze(0)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((block_q,), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)

    if causal:
        # Skip kv blocks strictly above the causal diagonal for this q block.
        upper = jax.lax.div(
            (q_blk_idx + 1) * block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, num_kv_blocks)
    else:
        upper = num_kv_blocks
    o, m, l = jax.lax.fori_loop(0, upper, body, (o0, m0, l0))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (o / safe_l[:, None]).astype(o_ref.dtype)


def _pallas_flash(q, k, v, causal: bool, block_q: int, block_k: int,
                  interpret: bool = False):
    from jax.experimental import pallas as pl

    *batch, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    bq = min(block_q, q_len)
    bk = min(block_k, kv_len)
    if q_len % bq or kv_len % bk:
        raise ValueError('sequence lengths must be divisible by block sizes '
                         '(q: {} % {}, kv: {} % {})'.format(q_len, bq, kv_len, bk))
    flat = int(jnp.prod(jnp.asarray(batch))) if batch else 1
    qf = q.reshape(flat, q_len, head_dim)
    kf = k.reshape(flat, kv_len, head_dim)
    vf = v.reshape(flat, kv_len, head_dim)
    scale = 1.0 / math.sqrt(head_dim)

    kernel = functools.partial(_flash_kernel, block_k=bk, causal=causal,
                               scale=scale, q_seq_len=q_len, kv_seq_len=kv_len,
                               block_q=bq)
    out = pl.pallas_call(
        kernel,
        grid=(flat, q_len // bq),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, kv_len, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, kv_len, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((flat, q_len, head_dim), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(q.shape)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 256,
                    block_k: int = 512, backend: Optional[str] = None):
    """Fused attention over ``(..., L, D)`` inputs.

    ``backend``: 'pallas' forces the TPU kernel, 'jnp' the scan fallback,
    'interpret' the Pallas interpreter (CI on CPU); default picks Pallas on TPU.
    """
    if backend is None:
        backend = 'pallas' if jax.default_backend() == 'tpu' else 'jnp'
    if backend == 'pallas':
        return _pallas_flash(q, k, v, causal, block_q, block_k)
    if backend == 'interpret':
        return _pallas_flash(q, k, v, causal, block_q, block_k, interpret=True)
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k)
