"""Attention kernels.

``flash_attention`` dispatches to a Pallas TPU kernel (online-softmax, never
materializes the (L, L) score matrix in HBM) and falls back to a
``lax.scan``-based blockwise jnp implementation on other backends. Both share
the same math, so tests can assert the Pallas path against the fallback.

The blockwise core is also the per-step building block of ring attention
(``petastorm_tpu/parallel/ring.py``): one (q-chunk, kv-chunk) partial update of
the running (o, m, l) accumulators.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _tpu_compiler_params(pltpu, **kwargs):
    """``pltpu.CompilerParams`` across jax versions (the 0.4.x line spells
    it ``TPUCompilerParams``); one resolution point for every pallas_call."""
    cls = getattr(pltpu, 'CompilerParams', None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise jnp core
# ---------------------------------------------------------------------------

def _block_update(q, k, v, o, m, l, scale, mask):
    """One online-softmax update: attend q against (k, v) and fold into the
    running (o, m, l) accumulators. Shapes: q (..., Lq, D), k/v (..., Lk, D),
    o (..., Lq, D), m/l (..., Lq)."""
    s = jnp.einsum('...qd,...kd->...qk', q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(-inf - (-inf)) -> exp(0); zero them via l
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum('...qk,...kd->...qd', p, v)
    return o_new, m_new, l_new


def attention_accumulators(q_len: int, head_dim: int, batch_shape=()):
    """Fresh (o, m, l) accumulators for online-softmax accumulation."""
    o = jnp.zeros(batch_shape + (q_len, head_dim), dtype=jnp.float32)
    m = jnp.full(batch_shape + (q_len,), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros(batch_shape + (q_len,), dtype=jnp.float32)
    return o, m, l


def finalize_attention(o, l):
    """Normalize accumulated output; fully-masked rows yield zeros."""
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return o / safe_l[..., None]


def attention_block_step(q, k, v, o, m, l, *, scale=None,
                         q_positions=None, k_positions=None, causal=True):
    """Public building block used by ring attention: fold one kv chunk into the
    accumulators, masking by absolute token positions when ``causal``."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mask = None
    if causal:
        if q_positions is None or k_positions is None:
            raise ValueError('causal masking needs q_positions/k_positions')
        mask = q_positions[..., :, None] >= k_positions[..., None, :]
    return _block_update(q, k, v, o, m, l, scale, mask)


def _pad_kv(k32, v32, block_k: int):
    """Pad k/v along the sequence dim to a block multiple; returns
    (k, v, num_blocks)."""
    k_len = k32.shape[-2]
    pad = (-k_len) % block_k
    if pad:
        pad_width = [(0, 0)] * (k32.ndim - 2) + [(0, pad), (0, 0)]
        k32 = jnp.pad(k32, pad_width)
        v32 = jnp.pad(v32, pad_width)
    return k32, v32, (k_len + pad) // block_k


def _to_kv_blocks(x, num_blocks: int, block_k: int):
    """(..., nb*bk, D) -> (nb, ..., bk, D) for scanning."""
    x = jnp.moveaxis(x, -2, 0)
    x = x.reshape((num_blocks, block_k) + x.shape[1:])
    return jnp.moveaxis(x, 1, -2)


def _from_kv_blocks(xb, num_blocks: int, block_k: int):
    """Inverse of :func:`_to_kv_blocks`."""
    xb = jnp.moveaxis(xb, -2, 1)
    xb = xb.reshape((num_blocks * block_k,) + xb.shape[2:])
    return jnp.moveaxis(xb, 0, -2)


def _kv_block_mask(q_pos, blk_idx, block_k: int, kv_len: int, causal: bool,
                   window=None):
    """(Lq, bk) validity mask for one kv block: tail padding + causality +
    optional sliding window (attend only the last ``window`` positions)."""
    k_pos = blk_idx * block_k + jnp.arange(block_k)
    mask = jnp.broadcast_to(k_pos[None, :] < kv_len, (q_pos.shape[0], block_k))
    if causal:
        mask = mask & (q_pos[:, None] >= k_pos[None, :])
    if window is not None:
        mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
    return mask


def _seg_to_kv_blocks(seg, num_blocks: int, block_k: int, pad_value: int):
    """(..., L) segment ids → (nb, ..., bk) blocks (tail padded with a
    sentinel that never equals a real segment)."""
    pad = num_blocks * block_k - seg.shape[-1]
    if pad:
        seg = jnp.pad(seg, [(0, 0)] * (seg.ndim - 1) + [(0, pad)],
                      constant_values=pad_value)
    seg = jnp.moveaxis(seg, -1, 0)
    seg = seg.reshape((num_blocks, block_k) + seg.shape[1:])
    return jnp.moveaxis(seg, 1, -1)


def _segment_mask(seg_q, seg_k_blk):
    """(..., Lq) × (..., bk) → (..., Lq, bk) same-segment mask."""
    return seg_q[..., :, None] == seg_k_blk[..., None, :]


def _normalize_seg(seg, target_ndim: int, length: int, name: str):
    """Validate a segment-id array's sequence length and insert singleton
    head/batch axes until it broadcasts against ``(..., L)`` operands of
    ``target_ndim`` dims — callers pass ``(B, L)``, ``(L,)`` or the full
    per-head shape interchangeably. Ids must be non-negative (negative values
    collide with the internal pad sentinels); checked only for host-side
    inputs (lists/numpy) — validating a concrete on-device array would force
    a device→host sync per layer per eager step, so device arrays and
    tracers rely on the documented contract."""
    host_side = not isinstance(seg, jax.Array)
    if host_side:
        import numpy as _np
        if (_np.asarray(seg) < 0).any():
            raise ValueError('%s must be non-negative (negative ids collide '
                             'with internal padding sentinels)' % name)
    seg = jnp.asarray(seg)
    if seg.shape[-1] != length or seg.ndim > target_ndim:
        raise ValueError(
            '%s must have shape (..., %d) broadcastable over the attention '
            'operands; got %r' % (name, length, seg.shape))
    while seg.ndim < target_ndim:
        seg = seg[..., None, :]
    return seg


def _repeat_kv_seg(kv_seg, k, group: int):
    """When the jnp GQA fallback head-repeats k/v, a PER-HEAD kv segment-id
    array (carrying the kv head axis) must be repeated the same way; head-free
    ``(B, L)`` / ``(L,)`` ids broadcast and pass through unchanged."""
    if kv_seg is None or group == 1:
        return kv_seg
    kv_seg = jnp.asarray(kv_seg)
    if kv_seg.ndim >= k.ndim - 1 and kv_seg.shape[-2] == k.shape[-3]:
        return jnp.repeat(kv_seg, group, axis=-2)
    return kv_seg


def _check_window(window, causal: bool):
    """Sliding-window attention is defined here as Mistral-style: each token
    attends the previous ``window`` positions, which only makes sense under
    causal masking."""
    if window is None:
        return
    if not causal:
        raise ValueError('window requires causal=True (sliding-window '
                         'attention looks back, not around)')
    if window < 1:
        raise ValueError('window must be >= 1, got %r' % (window,))


def _resolve_segs(segment_ids, kv_segment_ids, q_ndim: int, k_ndim: int,
                  q_len: int, kv_len: int):
    """ONE definition of segment-argument semantics for every path (jnp
    blockwise, jnp backward, Pallas forward/backward): kv ids default to the
    q ids; kv-only masking is rejected loudly instead of silently ignored.
    Returns ``(seg_q, kv_seg)`` normalized, or ``(None, None)``."""
    if segment_ids is None:
        if kv_segment_ids is not None:
            raise ValueError('kv_segment_ids requires segment_ids (kv-only '
                             'masking has no q-side ids to compare against)')
        return None, None
    seg_q = _normalize_seg(segment_ids, q_ndim - 1, q_len, 'segment_ids')
    kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
    kv_seg = _normalize_seg(kv_seg, k_ndim - 1, kv_len, 'kv_segment_ids')
    return seg_q, kv_seg


def blockwise_attention(q, k, v, *, causal: bool = True, block_k: int = 512,
                        segment_ids=None, kv_segment_ids=None, window=None):
    """Memory-efficient attention: scan over key/value blocks with online
    softmax. Works on any backend; O(L·block_k) live memory per head.

    Shapes: q/k/v ``(..., L, D)``; returns ``(..., L, D)`` in q's dtype.
    ``segment_ids`` ``(..., Lq)`` restricts attention to same-segment pairs
    (packed sequences); ``kv_segment_ids`` defaults to ``segment_ids``.
    ``window`` restricts each token to the last ``window`` positions
    (sliding-window/local attention; requires ``causal=True``).
    """
    _check_window(window, causal)
    orig_dtype = q.dtype
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / math.sqrt(q.shape[-1])
    q_len, k_len = q.shape[-2], k.shape[-2]
    batch_shape = q.shape[:-2]

    k32, v32, num_blocks = _pad_kv(k32, v32, block_k)
    kb = _to_kv_blocks(k32, num_blocks, block_k)
    vb = _to_kv_blocks(v32, num_blocks, block_k)
    seg_q, kv_seg = _resolve_segs(segment_ids, kv_segment_ids, q.ndim,
                                  k.ndim, q_len, k_len)
    if seg_q is not None:
        segb = _seg_to_kv_blocks(kv_seg, num_blocks, block_k, pad_value=-2)
    q_pos = jnp.arange(q_len)
    o, m, l = attention_accumulators(q_len, q.shape[-1], batch_shape)

    def step(carry, inputs):
        o, m, l = carry
        if segment_ids is not None:
            k_blk, v_blk, seg_blk, blk_idx = inputs
            mask = (_kv_block_mask(q_pos, blk_idx, block_k, k_len, causal,
                                   window)
                    & _segment_mask(seg_q, seg_blk))
        else:
            k_blk, v_blk, blk_idx = inputs
            mask = _kv_block_mask(q_pos, blk_idx, block_k, k_len, causal,
                                  window)
        o, m, l = _block_update(q32, k_blk, v_blk, o, m, l, scale, mask)
        return (o, m, l), None

    xs = ((kb, vb, segb, jnp.arange(num_blocks)) if segment_ids is not None
          else (kb, vb, jnp.arange(num_blocks)))
    (o, m, l), _ = jax.lax.scan(step, (o, m, l), xs)
    return finalize_attention(o, l).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, *refs, block_q: int,
                  block_k: int, causal: bool, scale: float, kv_seq_len: int,
                  num_kv_blocks: int, with_lse: bool, segmented: bool = False,
                  window=None):
    """One (batch·head, q-block, kv-block) grid step.

    KV **streams through the grid**: each program sees only a (block_k, D)
    slice of k/v in VMEM — bounded VMEM at any sequence length (the previous
    revision pinned the full kv sequence per program, ~2·L·D·4B, which blew
    VMEM exactly in the long-context regime the kernel exists for). The
    online-softmax accumulators (o, m, l) persist across the sequential
    kv-block grid dimension in VMEM scratch; the final kv step normalizes and
    writes the output block plus its logsumexp (saved for the backward).
    ``segmented`` adds per-token segment ids (packed sequences): pairs in
    different segments are masked out.
    """
    from jax.experimental import pallas as pl

    if segmented:
        segq_ref, segkv_ref, *refs = refs
    else:
        segq_ref = segkv_ref = None
    o_ref, *refs = refs
    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        lse_ref, (acc_ref, m_ref, l_ref) = None, refs
    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # Skip kv blocks strictly above the causal diagonal for this q block;
        # a sliding window additionally skips blocks entirely behind it.
        needed = kv_idx * block_k <= (q_idx + 1) * block_q - 1
        if window is not None:
            needed &= (kv_idx + 1) * block_k - 1 >= q_idx * block_q - window + 1
    else:
        needed = kv_idx >= 0

    @pl.when(needed)
    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = k_pos < kv_seq_len                      # tail-padding mask
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            mask = mask & (q_pos >= k_pos)
            if window is not None:
                mask = mask & (q_pos - k_pos < window)
        if segmented:
            # segq (bq, 1); segkv stored sublane-replicated (8, bk)
            mask = mask & (segq_ref[...] == segkv_ref[0:1, :])
        mask = jnp.broadcast_to(mask, s.shape)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]                     # (bq, 1)
        l_prev = l_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                 # (bq, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kv_idx == num_kv_blocks - 1)
    def _final():
        l = l_ref[...][:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        if with_lse:
            lse = jnp.where(l == 0.0, jnp.float32(_NEG_INF),
                            m_ref[...][:, :1] + jnp.log(safe_l))
            # (bq, 128) lane-replicated: TPU blocks want last-two dims
            # (8, 128)-divisible, so a 1-D (bq,) output block is not lowerable.
            lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _out_vma(*arrays):
    """Union of the inputs' varying-mesh-axes sets, for pallas_call out_shapes
    under ``shard_map`` (its vma check requires outputs to declare how they
    vary across mesh axes; kernel outputs vary exactly over the axes the
    operands do). Returns None on jax versions without vma tracking."""
    try:
        sets = [frozenset(jax.typeof(a).vma) for a in arrays]
    except (AttributeError, TypeError):
        return None
    return frozenset().union(*sets)


def _sds(shape, dtype, vma):
    if not vma:   # outside shard_map (None) or fully replicated (empty)
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


class _FlashDims:
    """Shared clamp/pad/flatten preamble of the forward and backward Pallas
    calls — ONE definition of the block-clamping and padding policy, so the
    backward always recomputes p against residuals padded under exactly the
    forward's rules.

    ``pad_q_like``/``pad_kv_like`` zero-pad the sequence dim to a block
    multiple and flatten batch dims to ``(flat, L, D)``; ``pad_rows`` does
    the same for per-q-row vectors ``(..., Lq)`` → ``(flat, Lq, 1)``
    (zero pad: backward padded rows have q == do == 0, so p = exp(0 − 0)
    stays finite and every contribution vanishes).

    Grouped-query attention: when kv carries fewer heads than q (shapes
    equal except axis -3, q heads a multiple of kv heads), ``group`` > 1 and
    ``kv_program_index`` maps a q program to the kv row its head shares —
    the kernels read shared kv blocks directly instead of materializing
    ``jnp.repeat``-ed kv in HBM."""

    def __init__(self, q_shape, kv_shape, block_q: int, block_k: int):
        *batch, q_len, head_dim = q_shape
        *kv_batch, kv_len, kv_head_dim = kv_shape
        self.batch = tuple(batch)
        self.kv_batch = tuple(kv_batch)
        if self.batch == self.kv_batch:
            self.group = 1
        else:
            if (kv_head_dim != head_dim
                    or len(self.batch) != len(self.kv_batch)
                    or not self.batch
                    or self.batch[:-1] != self.kv_batch[:-1]
                    or self.kv_batch[-1] <= 0
                    or self.batch[-1] % self.kv_batch[-1] != 0):
                raise ValueError(
                    'q/kv batch dims must match, or differ only in the head '
                    'axis (-3) with q heads a multiple of kv heads (GQA); '
                    'got q %r vs kv %r' % (q_shape, kv_shape))
            self.group = self.batch[-1] // self.kv_batch[-1]
        self.n_q_heads = self.batch[-1] if self.batch else 1
        self.n_kv_heads = self.kv_batch[-1] if self.kv_batch else 1
        self.q_len, self.kv_len, self.head_dim = q_len, kv_len, head_dim
        self.bq = min(block_q, q_len)
        self.bk = min(block_k, kv_len)
        self.pad_q = (-q_len) % self.bq
        self.pad_k = (-kv_len) % self.bk
        self.pq_len, self.pk_len = q_len + self.pad_q, kv_len + self.pad_k
        self.flat = int(math.prod(batch)) if batch else 1
        self.kv_flat = int(math.prod(kv_batch)) if kv_batch else 1
        self.num_q_blocks = self.pq_len // self.bq
        self.num_kv_blocks = self.pk_len // self.bk
        self.scale = 1.0 / math.sqrt(head_dim)

    def kv_program_index(self):
        """flat q-program index → flat kv row index (identity unless GQA)."""
        if self.group == 1:
            return lambda b: b
        h, hkv, g = self.n_q_heads, self.n_kv_heads, self.group
        return lambda b: (b // h) * hkv + (b % h) // g

    def _pad_flatten(self, x, pad, plen, flat):
        if pad:
            x = jnp.pad(x, [(0, 0)] * (x.ndim - 2) + [(0, pad), (0, 0)])
        return x.reshape(flat, plen, self.head_dim)

    def pad_q_like(self, x):
        return self._pad_flatten(x, self.pad_q, self.pq_len, self.flat)

    def pad_kv_like(self, x):
        return self._pad_flatten(x, self.pad_k, self.pk_len, self.kv_flat)

    def pad_rows(self, x):
        if self.pad_q:
            x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, self.pad_q)])
        return x.astype(jnp.float32).reshape(self.flat, self.pq_len, 1)

    def unpad_q_like(self, x):
        return x[:, :self.q_len, :].reshape(
            self.batch + (self.q_len, self.head_dim))

    def unpad_kv_like(self, x):
        return x[:, :self.kv_len, :].reshape(
            self.kv_batch + (self.kv_len, self.head_dim))

    def pad_seg_q(self, seg):
        """Broadcast + pad q-side segment ids to ``(flat, pq_len, 1)`` int32
        (pad sentinel -1: padded q rows match nothing real)."""
        seg = jnp.broadcast_to(seg, self.batch + (self.q_len,))
        if self.pad_q:
            seg = jnp.pad(seg, [(0, 0)] * (seg.ndim - 1) + [(0, self.pad_q)],
                          constant_values=-1)
        return seg.astype(jnp.int32).reshape(self.flat, self.pq_len, 1)

    def pad_seg_kv(self, seg):
        """Broadcast + pad kv-side segment ids to ``(kv_flat, 8, pk_len)``
        int32 — sublane-replicated so the kernel's ``(8, bk)`` block is
        lowerable; pad sentinel -2 never equals a q-side id."""
        seg = jnp.broadcast_to(seg, self.kv_batch + (self.kv_len,))
        if self.pad_k:
            seg = jnp.pad(seg, [(0, 0)] * (seg.ndim - 1) + [(0, self.pad_k)],
                          constant_values=-2)
        seg = seg.astype(jnp.int32).reshape(self.kv_flat, 1, self.pk_len)
        return jnp.broadcast_to(seg, (self.kv_flat, 8, self.pk_len))

    def check_segment_blocks(self, interpret: bool):
        """The kv segment block rides with ``block_k`` lanes; Mosaic wants
        the lane dim a multiple of 128 (or the full array dim). Interpret
        mode has no such constraint."""
        if not interpret and self.bk % 128 != 0 and self.bk != self.pk_len:
            raise ValueError(
                'segment_ids on the TPU Pallas path need block_k %% 128 == 0 '
                '(got block_k=%d); use the default block sizes or interpret '
                'mode' % self.bk)

    def sum_head_groups(self, x):
        """Per-q-head kv gradients ``(flat, L, D)`` → per-kv-head
        ``(kv_flat, L, D)`` by summing each head group (identity when not
        GQA). Inputs should be float32 — the group sum happens before any
        cast back to the storage dtype."""
        if self.group == 1:
            return x
        b = self.flat // self.n_q_heads
        return x.reshape(b, self.n_kv_heads, self.group,
                         *x.shape[1:]).sum(axis=2).reshape(
                             self.kv_flat, *x.shape[1:])


def _pallas_flash(q, k, v, causal: bool, block_q: int, block_k: int,
                  interpret: bool = False, with_lse: bool = True,
                  segment_ids=None, kv_segment_ids=None, window=None):
    """Returns ``(o, lse)`` with o in q's dtype and lse float32 ``(..., Lq)``
    — lse is None when ``with_lse=False`` (the no-grad forward skips the
    lane-replicated lse write entirely). Non-block-divisible lengths are
    padded and the pad is masked/sliced. ``segment_ids`` masks cross-segment
    pairs (packed sequences)."""
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    dims = _FlashDims(q.shape, k.shape, block_q, block_k)
    batch, q_len, head_dim = dims.batch, dims.q_len, dims.head_dim
    kv_len, bq, bk, flat = dims.kv_len, dims.bq, dims.bk, dims.flat
    pq_len, num_kv_blocks = dims.pq_len, dims.num_kv_blocks
    scale = dims.scale
    kvmap = dims.kv_program_index()
    qf = dims.pad_q_like(q)
    kf = dims.pad_kv_like(k)
    vf = dims.pad_kv_like(v)
    seg_q, kv_seg = _resolve_segs(segment_ids, kv_segment_ids, q.ndim,
                                  k.ndim, q_len, kv_len)
    segmented = seg_q is not None
    in_specs = [
        pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (kvmap(b), j, 0)),
        pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (kvmap(b), j, 0)),
    ]
    inputs = [qf, kf, vf]
    if segmented:
        dims.check_segment_blocks(interpret)
        inputs += [dims.pad_seg_q(seg_q), dims.pad_seg_kv(kv_seg)]
        in_specs += [
            pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 8, bk), lambda b, i, j: (kvmap(b), 0, j)),
        ]

    kernel = functools.partial(
        _flash_kernel, block_q=bq, block_k=bk, causal=causal, scale=scale,
        kv_seq_len=kv_len, num_kv_blocks=num_kv_blocks, with_lse=with_lse,
        segmented=segmented, window=window)
    vma = _out_vma(q, k, v)
    out_specs = [pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0))]
    out_shape = [_sds((flat, pq_len, head_dim), q.dtype, vma)]
    if with_lse:
        out_specs.append(pl.BlockSpec((None, bq, 128), lambda b, i, j: (b, i, 0)))
        out_shape.append(_sds((flat, pq_len, 128), jnp.float32, vma))
    result = pl.pallas_call(
        kernel,
        grid=(flat, pq_len // bq, num_kv_blocks),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),   # o accumulator
            pltpu.VMEM((bq, 128), jnp.float32),        # running max (lanes equal)
            pltpu.VMEM((bq, 128), jnp.float32),        # running sum (lanes equal)
        ],
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*inputs)
    o = dims.unpad_q_like(result[0])
    if not with_lse:
        return o, None
    lse = result[1][:, :q_len, 0].reshape(batch + (q_len,))
    return o, lse


def _flash_backward(q, k, v, o, lse, do, *, causal: bool, block_k: int,
                    scale: Optional[float] = None, segment_ids=None,
                    kv_segment_ids=None, window=None):
    """Memory-efficient flash backward (any backend): scan over kv blocks,
    recomputing p from (q, k, lse); O(Lq·block_k) live memory.

    dq accumulates across blocks; dk/dv are block-local scan outputs.
    """
    orig_dtypes = (q.dtype, k.dtype, v.dtype)
    q32, k32, v32, o32, do32 = (x.astype(jnp.float32)
                                for x in (q, k, v, o, do))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    q_len, kv_len = q.shape[-2], k.shape[-2]
    bk = min(block_k, kv_len)
    k32, v32, num_blocks = _pad_kv(k32, v32, bk)
    kb = _to_kv_blocks(k32, num_blocks, bk)
    vb = _to_kv_blocks(v32, num_blocks, bk)
    seg_q, kv_seg = _resolve_segs(segment_ids, kv_segment_ids, q.ndim,
                                  k.ndim, q_len, kv_len)
    if seg_q is not None:
        segb = _seg_to_kv_blocks(kv_seg, num_blocks, bk, pad_value=-2)
    q_pos = jnp.arange(q_len)
    # D_i = rowsum(do_i * o_i) — the only residual beyond lse
    d_term = jnp.sum(do32 * o32, axis=-1)            # (..., Lq)

    def step(dq, inputs):
        if segment_ids is not None:
            k_blk, v_blk, seg_blk, blk_idx = inputs
            mask = (_kv_block_mask(q_pos, blk_idx, bk, kv_len, causal,
                                   window)
                    & _segment_mask(seg_q, seg_blk))
        else:
            k_blk, v_blk, blk_idx = inputs
            mask = _kv_block_mask(q_pos, blk_idx, bk, kv_len, causal, window)
        s = jnp.einsum('...qd,...kd->...qk', q32, k_blk) * scale
        p = jnp.exp(s - lse[..., None])
        p = jnp.where(jnp.broadcast_to(mask, p.shape), p, 0.0)
        dv_blk = jnp.einsum('...qk,...qd->...kd', p, do32)
        dp = jnp.einsum('...qd,...kd->...qk', do32, v_blk)
        ds = p * (dp - d_term[..., None]) * scale
        dq = dq + jnp.einsum('...qk,...kd->...qd', ds, k_blk)
        dk_blk = jnp.einsum('...qk,...qd->...kd', ds, q32)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros(q32.shape, jnp.float32)
    xs = ((kb, vb, segb, jnp.arange(num_blocks)) if segment_ids is not None
          else (kb, vb, jnp.arange(num_blocks)))
    dq, (dkb, dvb) = jax.lax.scan(step, dq0, xs)
    dk = _from_kv_blocks(dkb, num_blocks, bk)[..., :kv_len, :]
    dv = _from_kv_blocks(dvb, num_blocks, bk)[..., :kv_len, :]
    return (dq.astype(orig_dtypes[0]), dk.astype(orig_dtypes[1]),
            dv.astype(orig_dtypes[2]))


def _bwd_recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
                        q_idx, kv_idx, block_q: int, block_k: int,
                        causal: bool, scale: float, kv_seq_len: int,
                        segq_ref=None, segkv_ref=None, window=None):
    """Shared recomputation block of both backward kernels: rebuild the
    probabilities p = exp(s − lse) for one (q-block, kv-block) tile (masking
    kv tail padding, causality, and — when segment refs are given — packed
    cross-segment pairs; lse == _NEG_INF marks a fully-masked row
    — forward convention — and exp would overflow there, so it is gated out
    explicitly), then ds = p·(do·vᵀ − Δ)·scale. Returns float32 operand
    views plus (p, ds)."""
    q = q_ref[...].astype(jnp.float32)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[...]                              # (bq, 1) float32
    delta = delta_ref[...]                          # (bq, 1) float32
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    mask = k_pos < kv_seq_len
    if causal:
        q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        mask = mask & (q_pos >= k_pos)
        if window is not None:
            mask = mask & (q_pos - k_pos < window)
    if segq_ref is not None:
        mask = mask & (segq_ref[...] == segkv_ref[0:1, :])
    mask = jnp.broadcast_to(mask, s.shape)
    live = mask & jnp.broadcast_to(lse > _NEG_INF / 2, s.shape)
    p = jnp.where(live, jnp.exp(s - lse), 0.0)      # (bq, bk)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale                   # (bq, bk)
    return q, k, do, p, ds


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *refs, block_q: int, block_k: int,
                         causal: bool, scale: float, kv_seq_len: int,
                         num_kv_blocks: int, segmented: bool = False,
                         window=None):
    """dq pass: one (batch·head, q-block, kv-block) grid step; kv streams
    through the grid (like the forward), dq accumulates in VMEM scratch across
    the sequential kv dimension and is written on the final kv step.

    p is recomputed from (q, k, lse); ds = p·(do·vᵀ − Δ)·scale with
    Δ = rowsum(do·o) precomputed outside the kernel."""
    from jax.experimental import pallas as pl

    if segmented:
        segq_ref, segkv_ref, dq_ref, dq_acc = refs
    else:
        segq_ref = segkv_ref = None
        dq_ref, dq_acc = refs
    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if causal:
        needed = kv_idx * block_k <= (q_idx + 1) * block_q - 1
        if window is not None:
            needed &= (kv_idx + 1) * block_k - 1 >= q_idx * block_q - window + 1
    else:
        needed = kv_idx >= 0

    @pl.when(needed)
    def _compute():
        _, k, _, _, ds = _bwd_recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_idx=q_idx,
            kv_idx=kv_idx, block_q=block_q, block_k=block_k, causal=causal,
            scale=scale, kv_seq_len=kv_seq_len, segq_ref=segq_ref,
            segkv_ref=segkv_ref, window=window)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == num_kv_blocks - 1)
    def _final():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           *refs, block_q: int,
                           block_k: int, causal: bool, scale: float,
                           kv_seq_len: int, num_q_blocks: int,
                           segmented: bool = False, window=None):
    """dk/dv pass: one (batch·head, kv-block, q-block) grid step; q (and do,
    lse, Δ) stream through the grid, dk/dv accumulate in VMEM scratch across
    the sequential q dimension. Padded q rows carry do == 0, so they
    contribute nothing and need no extra mask."""
    from jax.experimental import pallas as pl

    if segmented:
        segq_ref, segkv_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        segq_ref = segkv_ref = None
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    kv_idx = pl.program_id(1)
    q_idx = pl.program_id(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        needed = (q_idx + 1) * block_q - 1 >= kv_idx * block_k
        if window is not None:
            needed &= (kv_idx + 1) * block_k - 1 >= q_idx * block_q - window + 1
    else:
        needed = q_idx >= 0

    @pl.when(needed)
    def _compute():
        q, _, do, p, ds = _bwd_recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, q_idx=q_idx,
            kv_idx=kv_idx, block_q=block_q, block_k=block_k, causal=causal,
            scale=scale, kv_seq_len=kv_seq_len, segq_ref=segq_ref,
            segkv_ref=segkv_ref, window=window)
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, D)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, D)

    @pl.when(q_idx == num_q_blocks - 1)
    def _final():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _prepare_flash_bwd_q_side(dims: '_FlashDims', q, o, lse, do):
    """The q-side backward operands (padded q/do and the per-row lse/Δ
    columns) — step-invariant in ring attention, so callers scanning over kv
    chunks hoist this out of the loop instead of re-padding and re-reducing
    Δ = rowsum(do·o) per chunk."""
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return (dims.pad_q_like(q), dims.pad_q_like(do), dims.pad_rows(lse),
            dims.pad_rows(delta))


def _pallas_flash_backward(q, k, v, o, lse, do, *, causal: bool, block_q: int,
                           block_k: int, interpret: bool = False,
                           segment_ids=None, kv_segment_ids=None,
                           window=None):
    """Fused flash backward: two Pallas kernels (dq; dk/dv), both streaming
    the non-owned operand through the grid — bounded VMEM at any length, like
    the forward. Returns (dq, dk, dv) in the input dtypes.

    lse/Δ ride as ``(flat, L, 1)`` arrays with ``(bq, 1)`` blocks — the lane
    dim of the block equals the full array dim, which Mosaic lowers without
    the 128-lane replication the forward's lse *output* needs."""
    dims = _FlashDims(q.shape, k.shape, block_q, block_k)
    prep = _prepare_flash_bwd_q_side(dims, q, o, lse, do)
    seg_q, kv_seg = _resolve_segs(segment_ids, kv_segment_ids, q.ndim,
                                  k.ndim, dims.q_len, dims.kv_len)
    segs = None
    if seg_q is not None:
        dims.check_segment_blocks(interpret)
        segs = (dims.pad_seg_q(seg_q), dims.pad_seg_kv(kv_seg))
    return _flash_backward_from_prepared(dims, prep, k, v, causal=causal,
                                         interpret=interpret, segs=segs,
                                         window=window)


def _flash_backward_from_prepared(dims: '_FlashDims', prep, k, v, *,
                                  causal: bool, interpret: bool = False,
                                  segs=None, window=None):
    """Backward kernels given pre-padded q-side operands (see
    :func:`_prepare_flash_bwd_q_side`); only the kv chunk varies per call.
    ``segs``: optional pre-padded ``(seg_q, seg_kv)`` from ``pad_seg_q`` /
    ``pad_seg_kv`` for packed-sequence masking.

    GQA: the dk/dv kernel runs one program per Q head (reading the shared kv
    row via the head map) and emits per-q-head float32 partials that are
    group-summed outside — a transient ``group``× float32 buffer, traded for
    never materializing repeated kv."""
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    qf, dof, lsef, deltaf = prep
    kv_len, head_dim, bq, bk = dims.kv_len, dims.head_dim, dims.bq, dims.bk
    flat, pq_len, pk_len = dims.flat, dims.pq_len, dims.pk_len
    num_q_blocks, num_kv_blocks = dims.num_q_blocks, dims.num_kv_blocks
    scale = dims.scale
    kvmap = dims.kv_program_index()
    kf = dims.pad_kv_like(k)
    vf = dims.pad_kv_like(v)
    vma = _out_vma(qf, k, v, dof)
    segmented = segs is not None

    qspec = pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0))
    kvspec_j = pl.BlockSpec((None, bk, head_dim),
                            lambda b, i, j: (kvmap(b), j, 0))
    rowspec_i = pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, i, 0))
    dq_inputs = [qf, kf, vf, dof, lsef, deltaf]
    dq_specs = [qspec, kvspec_j, kvspec_j, qspec, rowspec_i, rowspec_i]
    if segmented:
        dq_inputs += list(segs)
        dq_specs += [rowspec_i,
                     pl.BlockSpec((None, 8, bk),
                                  lambda b, i, j: (kvmap(b), 0, j))]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=bq, block_k=bk,
                          causal=causal, scale=scale, kv_seq_len=kv_len,
                          num_kv_blocks=num_kv_blocks, segmented=segmented,
                          window=window),
        grid=(flat, num_q_blocks, num_kv_blocks),
        in_specs=dq_specs,
        out_specs=qspec,
        out_shape=_sds((flat, pq_len, head_dim), qf.dtype, vma),
        scratch_shapes=[pltpu.VMEM((bq, head_dim), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*dq_inputs)

    qspec_j = pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, j, 0))
    kvspec_i = pl.BlockSpec((None, bk, head_dim),
                            lambda b, i, j: (kvmap(b), i, 0))
    outspec_i = pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, i, 0))
    rowspec_j = pl.BlockSpec((None, bq, 1), lambda b, i, j: (b, j, 0))
    dkdv_inputs = [qf, kf, vf, dof, lsef, deltaf]
    dkdv_specs = [qspec_j, kvspec_i, kvspec_i, qspec_j, rowspec_j, rowspec_j]
    if segmented:
        dkdv_inputs += list(segs)
        dkdv_specs += [rowspec_j,
                       pl.BlockSpec((None, 8, bk),
                                    lambda b, i, j: (kvmap(b), 0, i))]
    # GQA emits per-Q-head float32 partials (exact cross-head sum before the
    # storage cast); plain MHA writes k/v dtype directly — no extra HBM
    # traffic or cast pass on the common path
    part_dtypes = ((jnp.float32, jnp.float32) if dims.group > 1
                   else (k.dtype, v.dtype))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, block_q=bq, block_k=bk,
                          causal=causal, scale=scale, kv_seq_len=kv_len,
                          num_q_blocks=num_q_blocks, segmented=segmented,
                          window=window),
        grid=(flat, num_kv_blocks, num_q_blocks),
        in_specs=dkdv_specs,
        out_specs=[outspec_i, outspec_i],
        out_shape=[_sds((flat, pk_len, head_dim), part_dtypes[0], vma),
                   _sds((flat, pk_len, head_dim), part_dtypes[1], vma)],
        scratch_shapes=[pltpu.VMEM((bk, head_dim), jnp.float32),
                        pltpu.VMEM((bk, head_dim), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            pltpu,
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(*dkdv_inputs)

    if dims.group > 1:
        dk = dims.sum_head_groups(dk).astype(k.dtype)
        dv = dims.sum_head_groups(dv).astype(v.dtype)
    return dims.unpad_q_like(dq), dims.unpad_kv_like(dk), dims.unpad_kv_like(dv)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             block_q: int = 256, block_k: int = 512,
                             interpret: bool = False):
    """Pallas flash forward returning ``(o, lse)`` — the building block of
    ring attention's per-chunk computation: each visiting kv chunk is attended
    by the fused kernel, and the normalized per-chunk outputs are folded
    together with :func:`merge_attention_chunks`. Not differentiable on its
    own (ring attention wraps the whole chunk loop in a custom_vjp)."""
    return _pallas_flash(q, k, v, causal, block_q, block_k, interpret,
                         with_lse=True)


def flash_attention_chunk_grads(q, k, v, o, lse, do, *, causal: bool,
                                block_q: int = 256, block_k: int = 512,
                                interpret: bool = False):
    """Per-chunk-pair gradients via the fused backward kernels: given local
    queries (with their GLOBAL output o and logsumexp lse) against one kv
    chunk, returns (dq, dk, dv) for exactly that pair — p = exp(s − lse)
    already yields global softmax probabilities, so cross-chunk gradients
    need no further normalization."""
    return _pallas_flash_backward(q, k, v, o, lse, do, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  interpret=interpret)


def merge_attention_chunks(o_acc, m, l, o_i, lse_i):
    """Fold one finished attention chunk — normalized output ``o_i`` plus its
    per-row logsumexp ``lse_i`` — into running accumulators ``(o_acc, m, l)``.

    Contract: ``o_acc = Σ_j o_j·exp(lse_j − m)`` and ``l = Σ_j exp(lse_j − m)``
    with ``m = max_j lse_j``, so ``o_acc / l`` is the softmax-weighted merge
    (:func:`finalize_attention`) and ``m + log(l)`` the merged logsumexp.
    Fully-masked chunks carry ``lse_i == -inf`` and contribute weight 0; the
    exponents are never positive, so nothing overflows."""
    m_new = jnp.maximum(m, lse_i)
    corr = jnp.exp(m - m_new)
    w = jnp.exp(lse_i - m_new)
    o_acc = o_acc * corr[..., None] + o_i.astype(jnp.float32) * w[..., None]
    return o_acc, m_new, l * corr + w


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, seg_q, seg_kv, causal, block_q, block_k, interpret,
           bwd_backend, window):
    o, _ = _pallas_flash(q, k, v, causal, block_q, block_k, interpret,
                         with_lse=False, segment_ids=seg_q,
                         kv_segment_ids=seg_kv, window=window)
    return o


def _flash_fwd(q, k, v, seg_q, seg_kv, causal, block_q, block_k, interpret,
               bwd_backend, window):
    o, lse = _pallas_flash(q, k, v, causal, block_q, block_k, interpret,
                           segment_ids=seg_q, kv_segment_ids=seg_kv,
                           window=window)
    return o, (q, k, v, o, lse, seg_q, seg_kv)


def _flash_bwd(causal, block_q, block_k, interpret, bwd_backend, window, res,
               do):
    q, k, v, o, lse, seg_q, seg_kv = res
    if bwd_backend == 'pallas':
        grads = _pallas_flash_backward(q, k, v, o, lse, do, causal=causal,
                                       block_q=block_q, block_k=block_k,
                                       interpret=interpret, segment_ids=seg_q,
                                       kv_segment_ids=seg_kv, window=window)
        return grads + (None, None)
    if q.shape[:-2] != k.shape[:-2]:     # GQA through the jnp oracle:
        group = q.shape[-3] // k.shape[-3]
        kr = jnp.repeat(k, group, axis=-3)
        vr = jnp.repeat(v, group, axis=-3)
        seg_kv_r = _repeat_kv_seg(seg_kv, k, group)
        dq, dkr, dvr = _flash_backward(q, kr, vr, o, lse, do, causal=causal,
                                       block_k=block_k, segment_ids=seg_q,
                                       kv_segment_ids=seg_kv_r, window=window)
        shape = k.shape[:-3] + (k.shape[-3], group) + k.shape[-2:]
        dk = dkr.astype(jnp.float32).reshape(shape).sum(axis=-3)
        dv = dvr.astype(jnp.float32).reshape(shape).sum(axis=-3)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None
    dq, dk, dv = _flash_backward(q, k, v, o, lse, do, causal=causal,
                                 block_k=block_k, segment_ids=seg_q,
                                 kv_segment_ids=seg_kv, window=window)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 256,
                    block_k: int = 512, backend: Optional[str] = None,
                    bwd: Optional[str] = None, segment_ids=None,
                    kv_segment_ids=None, window: Optional[int] = None):
    """Fused attention over ``(..., L, D)`` inputs; differentiable (custom_vjp
    with fused Pallas backward kernels), any sequence length (padded to block
    multiples internally).

    Grouped-query attention: k/v may carry fewer heads than q (shapes equal
    except axis -3, q heads a multiple of kv heads). The Pallas path reads
    shared kv blocks via the head map — repeated kv is never materialized in
    HBM; the jnp fallback repeats kv explicitly.

    Packed sequences: ``segment_ids`` ``(..., Lq)`` (int; broadcastable over
    batch/head dims) masks attention to same-segment pairs — the contract
    for multi-document packing is that packed attention equals per-document
    attention (``tests/test_flash_segments.py``). ``kv_segment_ids``
    defaults to ``segment_ids``. On the TPU Pallas path ``block_k`` must be
    a multiple of 128 when segments are used (the defaults are).

    ``backend``: 'pallas' forces the TPU kernel, 'jnp' the scan fallback,
    'interpret' the Pallas interpreter (CI on CPU); default picks Pallas on TPU.
    ``bwd``: backward implementation for the Pallas path — 'pallas' (default;
    two fused kernels: dq with kv streaming, dk/dv with q streaming) or 'jnp'
    (``_flash_backward``, the memory-equivalent kv-block scan XLA compiles to
    fused ops — kept as an escape hatch and as the cross-check oracle in
    ``tests/test_flash_attention.py``).

    Measurement caveat: gradients are verified value-equal to reference
    attention on hardware, but kernel wall-times through this host's TPU
    tunnel are not trustworthy (block_until_ready acks early), so fwd/bwd
    speedup vs the XLA-compiled fallback is asserted by construction
    (single fused pass, no (L, L) materialization), not by a timing table.
    """
    if backend is None:
        backend = 'pallas' if jax.default_backend() == 'tpu' else 'jnp'
    if bwd not in (None, 'pallas', 'jnp'):
        raise ValueError("bwd must be 'pallas' or 'jnp', got %r" % (bwd,))
    _check_window(window, causal)
    if backend in ('pallas', 'interpret'):
        return _flash(q, k, v, segment_ids, kv_segment_ids, causal, block_q,
                      block_k, backend == 'interpret', bwd or 'pallas',
                      window)
    if bwd is not None:
        raise ValueError("bwd applies only to the Pallas path (backend "
                         "'pallas' or 'interpret'); the %r backend "
                         "differentiates blockwise_attention directly"
                         % backend)
    if q.shape[:-2] != k.shape[:-2]:     # GQA on the jnp path: repeat kv
        _FlashDims(q.shape, k.shape, block_q, block_k)   # validates shapes
        group = q.shape[-3] // k.shape[-3]
        kv_segment_ids = _repeat_kv_seg(kv_segment_ids, k, group)
        k = jnp.repeat(k, group, axis=-3)
        v = jnp.repeat(v, group, axis=-3)
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k,
                               segment_ids=segment_ids,
                               kv_segment_ids=kv_segment_ids, window=window)
