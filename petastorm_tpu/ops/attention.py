"""Attention kernels.

``flash_attention`` dispatches to a Pallas TPU kernel (online-softmax, never
materializes the (L, L) score matrix in HBM) and falls back to a
``lax.scan``-based blockwise jnp implementation on other backends. Both share
the same math, so tests can assert the Pallas path against the fallback.

The blockwise core is also the per-step building block of ring attention
(``petastorm_tpu/parallel/ring.py``): one (q-chunk, kv-chunk) partial update of
the running (o, m, l) accumulators.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise jnp core
# ---------------------------------------------------------------------------

def _block_update(q, k, v, o, m, l, scale, mask):
    """One online-softmax update: attend q against (k, v) and fold into the
    running (o, m, l) accumulators. Shapes: q (..., Lq, D), k/v (..., Lk, D),
    o (..., Lq, D), m/l (..., Lq)."""
    s = jnp.einsum('...qd,...kd->...qk', q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(-inf - (-inf)) -> exp(0); zero them via l
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum('...qk,...kd->...qd', p, v)
    return o_new, m_new, l_new


def attention_accumulators(q_len: int, head_dim: int, batch_shape=()):
    """Fresh (o, m, l) accumulators for online-softmax accumulation."""
    o = jnp.zeros(batch_shape + (q_len, head_dim), dtype=jnp.float32)
    m = jnp.full(batch_shape + (q_len,), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros(batch_shape + (q_len,), dtype=jnp.float32)
    return o, m, l


def finalize_attention(o, l):
    """Normalize accumulated output; fully-masked rows yield zeros."""
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return o / safe_l[..., None]


def attention_block_step(q, k, v, o, m, l, *, scale=None,
                         q_positions=None, k_positions=None, causal=True):
    """Public building block used by ring attention: fold one kv chunk into the
    accumulators, masking by absolute token positions when ``causal``."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    mask = None
    if causal:
        if q_positions is None or k_positions is None:
            raise ValueError('causal masking needs q_positions/k_positions')
        mask = q_positions[..., :, None] >= k_positions[..., None, :]
    return _block_update(q, k, v, o, m, l, scale, mask)


def _pad_kv(k32, v32, block_k: int):
    """Pad k/v along the sequence dim to a block multiple; returns
    (k, v, num_blocks)."""
    k_len = k32.shape[-2]
    pad = (-k_len) % block_k
    if pad:
        pad_width = [(0, 0)] * (k32.ndim - 2) + [(0, pad), (0, 0)]
        k32 = jnp.pad(k32, pad_width)
        v32 = jnp.pad(v32, pad_width)
    return k32, v32, (k_len + pad) // block_k


def _to_kv_blocks(x, num_blocks: int, block_k: int):
    """(..., nb*bk, D) -> (nb, ..., bk, D) for scanning."""
    x = jnp.moveaxis(x, -2, 0)
    x = x.reshape((num_blocks, block_k) + x.shape[1:])
    return jnp.moveaxis(x, 1, -2)


def _from_kv_blocks(xb, num_blocks: int, block_k: int):
    """Inverse of :func:`_to_kv_blocks`."""
    xb = jnp.moveaxis(xb, -2, 1)
    xb = xb.reshape((num_blocks * block_k,) + xb.shape[2:])
    return jnp.moveaxis(xb, 0, -2)


def _kv_block_mask(q_pos, blk_idx, block_k: int, kv_len: int, causal: bool):
    """(Lq, bk) validity mask for one kv block: tail padding + causality."""
    k_pos = blk_idx * block_k + jnp.arange(block_k)
    mask = jnp.broadcast_to(k_pos[None, :] < kv_len, (q_pos.shape[0], block_k))
    if causal:
        mask = mask & (q_pos[:, None] >= k_pos[None, :])
    return mask


def blockwise_attention(q, k, v, *, causal: bool = True, block_k: int = 512):
    """Memory-efficient attention: scan over key/value blocks with online
    softmax. Works on any backend; O(L·block_k) live memory per head.

    Shapes: q/k/v ``(..., L, D)``; returns ``(..., L, D)`` in q's dtype.
    """
    orig_dtype = q.dtype
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / math.sqrt(q.shape[-1])
    q_len, k_len = q.shape[-2], k.shape[-2]
    batch_shape = q.shape[:-2]

    k32, v32, num_blocks = _pad_kv(k32, v32, block_k)
    kb = _to_kv_blocks(k32, num_blocks, block_k)
    vb = _to_kv_blocks(v32, num_blocks, block_k)
    q_pos = jnp.arange(q_len)
    o, m, l = attention_accumulators(q_len, q.shape[-1], batch_shape)

    def step(carry, inputs):
        o, m, l = carry
        k_blk, v_blk, blk_idx = inputs
        mask = _kv_block_mask(q_pos, blk_idx, block_k, k_len, causal)
        o, m, l = _block_update(q32, k_blk, v_blk, o, m, l, scale, mask)
        return (o, m, l), None

    (o, m, l), _ = jax.lax.scan(step, (o, m, l),
                                (kb, vb, jnp.arange(num_blocks)))
    return finalize_attention(o, l).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *refs, block_q: int,
                  block_k: int, causal: bool, scale: float, kv_seq_len: int,
                  num_kv_blocks: int, with_lse: bool):
    """One (batch·head, q-block, kv-block) grid step.

    KV **streams through the grid**: each program sees only a (block_k, D)
    slice of k/v in VMEM — bounded VMEM at any sequence length (the previous
    revision pinned the full kv sequence per program, ~2·L·D·4B, which blew
    VMEM exactly in the long-context regime the kernel exists for). The
    online-softmax accumulators (o, m, l) persist across the sequential
    kv-block grid dimension in VMEM scratch; the final kv step normalizes and
    writes the output block plus its logsumexp (saved for the backward).
    """
    from jax.experimental import pallas as pl

    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        lse_ref, (acc_ref, m_ref, l_ref) = None, refs
    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # Skip kv blocks strictly above the causal diagonal for this q block.
        needed = kv_idx * block_k <= (q_idx + 1) * block_q - 1
    else:
        needed = kv_idx >= 0

    @pl.when(needed)
    def _compute():
        q = q_ref[...].astype(jnp.float32)
        k = k_ref[...].astype(jnp.float32)
        v = v_ref[...].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = k_pos < kv_seq_len                      # tail-padding mask
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            mask = mask & (q_pos >= k_pos)
        mask = jnp.broadcast_to(mask, s.shape)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[...][:, :1]                     # (bq, 1)
        l_prev = l_ref[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                 # (bq, 1)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kv_idx == num_kv_blocks - 1)
    def _final():
        l = l_ref[...][:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        if with_lse:
            lse = jnp.where(l == 0.0, jnp.float32(_NEG_INF),
                            m_ref[...][:, :1] + jnp.log(safe_l))
            # (bq, 128) lane-replicated: TPU blocks want last-two dims
            # (8, 128)-divisible, so a 1-D (bq,) output block is not lowerable.
            lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _pallas_flash(q, k, v, causal: bool, block_q: int, block_k: int,
                  interpret: bool = False, with_lse: bool = True):
    """Returns ``(o, lse)`` with o in q's dtype and lse float32 ``(..., Lq)``
    — lse is None when ``with_lse=False`` (the no-grad forward skips the
    lane-replicated lse write entirely). Non-block-divisible lengths are
    padded and the pad is masked/sliced."""
    from jax.experimental import pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    *batch, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    bq = min(block_q, q_len)
    bk = min(block_k, kv_len)
    pad_q = (-q_len) % bq
    pad_k = (-kv_len) % bk
    if pad_q:
        pad_width = [(0, 0)] * (q.ndim - 2) + [(0, pad_q), (0, 0)]
        q = jnp.pad(q, pad_width)
    if pad_k:
        pad_width = [(0, 0)] * (k.ndim - 2) + [(0, pad_k), (0, 0)]
        k = jnp.pad(k, pad_width)
        v = jnp.pad(v, pad_width)
    pq_len, pk_len = q_len + pad_q, kv_len + pad_k

    flat = int(math.prod(batch)) if batch else 1
    qf = q.reshape(flat, pq_len, head_dim)
    kf = k.reshape(flat, pk_len, head_dim)
    vf = v.reshape(flat, pk_len, head_dim)
    scale = 1.0 / math.sqrt(head_dim)
    num_kv_blocks = pk_len // bk

    kernel = functools.partial(
        _flash_kernel, block_q=bq, block_k=bk, causal=causal, scale=scale,
        kv_seq_len=kv_len, num_kv_blocks=num_kv_blocks, with_lse=with_lse)
    out_specs = [pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((flat, pq_len, head_dim), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((None, bq, 128), lambda b, i, j: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((flat, pq_len, 128), jnp.float32))
    result = pl.pallas_call(
        kernel,
        grid=(flat, pq_len // bq, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),   # o accumulator
            pltpu.VMEM((bq, 128), jnp.float32),        # running max (lanes equal)
            pltpu.VMEM((bq, 128), jnp.float32),        # running sum (lanes equal)
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(qf, kf, vf)
    o = result[0][:, :q_len, :].reshape(tuple(batch) + (q_len, head_dim))
    if not with_lse:
        return o, None
    lse = result[1][:, :q_len, 0].reshape(tuple(batch) + (q_len,))
    return o, lse


def _flash_backward(q, k, v, o, lse, do, *, causal: bool, block_k: int,
                    scale: Optional[float] = None):
    """Memory-efficient flash backward (any backend): scan over kv blocks,
    recomputing p from (q, k, lse); O(Lq·block_k) live memory.

    dq accumulates across blocks; dk/dv are block-local scan outputs.
    """
    orig_dtypes = (q.dtype, k.dtype, v.dtype)
    q32, k32, v32, o32, do32 = (x.astype(jnp.float32)
                                for x in (q, k, v, o, do))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    q_len, kv_len = q.shape[-2], k.shape[-2]
    bk = min(block_k, kv_len)
    k32, v32, num_blocks = _pad_kv(k32, v32, bk)
    kb = _to_kv_blocks(k32, num_blocks, bk)
    vb = _to_kv_blocks(v32, num_blocks, bk)
    q_pos = jnp.arange(q_len)
    # D_i = rowsum(do_i * o_i) — the only residual beyond lse
    d_term = jnp.sum(do32 * o32, axis=-1)            # (..., Lq)

    def step(dq, inputs):
        k_blk, v_blk, blk_idx = inputs
        mask = _kv_block_mask(q_pos, blk_idx, bk, kv_len, causal)
        s = jnp.einsum('...qd,...kd->...qk', q32, k_blk) * scale
        p = jnp.exp(s - lse[..., None])
        p = jnp.where(jnp.broadcast_to(mask, p.shape), p, 0.0)
        dv_blk = jnp.einsum('...qk,...qd->...kd', p, do32)
        dp = jnp.einsum('...qd,...kd->...qk', do32, v_blk)
        ds = p * (dp - d_term[..., None]) * scale
        dq = dq + jnp.einsum('...qk,...kd->...qd', ds, k_blk)
        dk_blk = jnp.einsum('...qk,...qd->...kd', ds, q32)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros(q32.shape, jnp.float32)
    dq, (dkb, dvb) = jax.lax.scan(step, dq0,
                                  (kb, vb, jnp.arange(num_blocks)))
    dk = _from_kv_blocks(dkb, num_blocks, bk)[..., :kv_len, :]
    dv = _from_kv_blocks(dvb, num_blocks, bk)[..., :kv_len, :]
    return (dq.astype(orig_dtypes[0]), dk.astype(orig_dtypes[1]),
            dv.astype(orig_dtypes[2]))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _pallas_flash(q, k, v, causal, block_q, block_k, interpret,
                         with_lse=False)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _pallas_flash(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, do, causal=causal, block_k=block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 256,
                    block_k: int = 512, backend: Optional[str] = None):
    """Fused attention over ``(..., L, D)`` inputs; differentiable (custom_vjp
    with a flash-style blockwise backward), any sequence length (padded to
    block multiples internally).

    ``backend``: 'pallas' forces the TPU kernel, 'jnp' the scan fallback,
    'interpret' the Pallas interpreter (CI on CPU); default picks Pallas on TPU.

    Design note: only the FORWARD runs as a Pallas kernel. The backward
    (``_flash_backward``) is a memory-efficient jnp kv-block scan that XLA
    compiles to fused ops — same O(Lq·block_k) live memory as a hand-written
    kernel, gradients verified equal to reference attention on hardware
    (``tests/test_flash_attention.py``), but it is not a fused Pallas kernel.
    Training-step perf parity of ``attention='flash'`` vs 'blockwise' is
    unmeasured: kernel wall-times through this host's TPU tunnel are not
    trustworthy (block_until_ready acks early), so only value correctness is
    claimed here.
    """
    if backend is None:
        backend = 'pallas' if jax.default_backend() == 'tpu' else 'jnp'
    if backend in ('pallas', 'interpret'):
        return _flash(q, k, v, causal, block_q, block_k, backend == 'interpret')
    return blockwise_attention(q, k, v, causal=causal, block_k=block_k)
