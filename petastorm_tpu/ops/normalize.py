"""Fused image normalization: uint8 HWC batches → scaled bfloat16/float32.

The classic first op of every vision input pipeline ((x/255 - mean) / std).
Doing it on device right after infeed keeps the host→HBM transfer at 1
byte/pixel (uint8) instead of 4 (float32) — a 4× infeed bandwidth win, which is
exactly the bottleneck the reference's CPU-side decode pipeline fights.

Pallas kernel on TPU (single fused VPU pass), jnp elsewhere (XLA fuses it too;
the kernel exists to guarantee the fusion and to skip the f32 intermediate).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _normalize_kernel(x_ref, mean_ref, inv_std_ref, o_ref):
    x = x_ref[...].astype(jnp.float32) * (1.0 / 255.0)
    o_ref[...] = ((x - mean_ref[...]) * inv_std_ref[...]).astype(o_ref.dtype)


def normalize_images(images, mean=(0.485, 0.456, 0.406),
                     std=(0.229, 0.224, 0.225), dtype=jnp.bfloat16,
                     backend=None):
    """Normalize a uint8 image batch ``(N, H, W, C)`` to ``dtype``.

    ``backend``: 'pallas' | 'jnp' | 'interpret'; default picks pallas on TPU.
    """
    if backend is None:
        backend = 'pallas' if jax.default_backend() == 'tpu' else 'jnp'
    mean_arr = jnp.asarray(mean, dtype=jnp.float32)
    inv_std = 1.0 / jnp.asarray(std, dtype=jnp.float32)
    if backend == 'jnp':
        x = images.astype(jnp.float32) / 255.0
        return ((x - mean_arr) * inv_std).astype(dtype)

    from jax.experimental import pallas as pl

    n, h, w, c = images.shape
    flat = images.reshape(n, h * w * c)
    mean_row = jnp.tile(mean_arr, h * w)
    inv_row = jnp.tile(inv_std, h * w)
    out = pl.pallas_call(
        _normalize_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((None, h * w * c), lambda i: (i, 0)),
            pl.BlockSpec((h * w * c,), lambda i: (0,)),
            pl.BlockSpec((h * w * c,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((None, h * w * c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h * w * c), dtype),
        interpret=(backend == 'interpret'),
    )(flat, mean_row, inv_row)
    return out.reshape(n, h, w, c)
