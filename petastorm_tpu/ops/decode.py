"""Device-side decode: jittable decoders for bytes-through columns.

BENCH_r13 closed the host half of the decode wall and named what remains:
per-cell codec cost dominates small payloads, and thread workers convoy on
the GIL around sub-quantum decode calls. Both walls fall the same way —
stop decoding on the host. This module is the device half of that plan
(docs/decode.md "Device-side decode"):

- **Plan time** (:func:`plan_device_decode`): per column, decide at reader
  construction whether the raw stored payload can decode *on the
  accelerator* under ``jax.jit``. Eligibility is strict and static — the
  codec must expose a device plan (``NdarrayCodec`` today), the field must
  be fixed-shape, non-nullable, little-endian numeric, and no reader
  feature that needs decoded host values (predicates, NGram windows,
  per-field decode hints, a host ``TransformSpec``) may be in play. A
  column that fails planning **declines to the host path; it never owns an
  error**.
- **Ship time** (:func:`raw_column_view`): workers skip host decode for
  planned columns and ship the raw arrow payload as one ``(n, stride)``
  uint8 grid — zero-copy out of the arrow data buffer and zero-copy
  through the multipart transport. Validation failures (header drift,
  nulls that appeared at read time) re-decode on the host and
  :func:`repack_to_raw` so a column's representation stays uniform for the
  reader's lifetime (the shuffling buffers preallocate per-column storage
  from the first chunk's dtype).
- **Decode time** (:func:`build_fused_infeed`): the strict v1 ``np.save``
  header parser (``codecs._parse_fast_npy_header``) proves fixed-shape
  cells share identical header bytes, so device decode is a header-strip +
  ``lax.bitcast_convert_type`` + reshape over the stacked uint8 buffer —
  one jitted program, fused with a device-flagged ``TransformSpec`` on the
  staging stream. :func:`decode_raw_host` is the bit-identical numpy
  reference (property-tested in ``tests/test_device_decode.py``) and the
  host fallback when no loader claims the raw columns.

Kill switch: ``PETASTORM_TPU_DEVICE_DECODE`` (default on where eligible),
read once per reader at plan time — the uniform switch shape
(``PETASTORM_TPU_BATCHED_DECODE``, ``_LINEAGE``, ``_PROFILER``).
"""

from __future__ import annotations

import io
import os
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (BATCHED_DECODE_ENV_VAR,
                                  _parse_fast_npy_header,
                                  batched_decode_enabled, split_binary_chunk)

#: Environment variable gating the device-decode path (default on where
#: eligible). ``0``/``false``/``off`` plans nothing, so every column keeps
#: the host batched/per-cell matrix. Read once per reader at plan time.
DEVICE_DECODE_ENV_VAR = 'PETASTORM_TPU_DEVICE_DECODE'


def device_decode_enabled() -> bool:
    """The :data:`DEVICE_DECODE_ENV_VAR` gate (default on)."""
    value = os.environ.get(DEVICE_DECODE_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def jax_x64_enabled() -> bool:
    """True when jax keeps 64-bit dtypes (``JAX_ENABLE_X64``). Without it
    jax canonicalizes i8/u8/f8-descr arrays to their 32-bit cousins, so a
    bitcast decode of an 8-byte column cannot be bit-identical — those
    columns must decline at plan time."""
    try:
        import jax
        return bool(jax.config.jax_enable_x64)
    except Exception:  # noqa: BLE001 - any failure means "decline"
        return False


def jax_backend_available() -> bool:
    """True when jax imports AND a backend initializes. Device planning
    must decline (not error) on a host with no accelerator runtime and no
    CPU fallback — the reader still works, through the host matrix."""
    try:
        import jax
        return len(jax.devices()) > 0
    except Exception:  # noqa: BLE001 - any backend failure means "decline"
        return False


class DeviceColumnPlan(NamedTuple):
    """Picklable per-column decode plan, computed once at reader
    construction and shipped to workers inside ``worker_args``.

    The plan pins the EXACT stored layout the raw path expects: every cell
    of the column is ``header`` (the byte-identical machine-generated
    ``np.save`` v1 prefix for ``(descr, shape)``) followed by
    ``stride - header_len`` payload bytes. Workers verify the pin per
    chunk (:func:`raw_column_view`) and repack via the host decoder when
    it does not hold."""

    name: str
    descr: str          # normalized dtype.str, e.g. '<f4' / '|u1'
    shape: Tuple[int, ...]
    header: bytes       # the full np.save v1 prefix (magic + len + dict)

    @property
    def header_len(self) -> int:
        return len(self.header)

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self.descr)

    @property
    def cell_count(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64))

    @property
    def cell_nbytes(self) -> int:
        return self.cell_count * self.dtype.itemsize

    @property
    def stride(self) -> int:
        return self.header_len + self.cell_nbytes


def npy_header_bytes(dtype: np.dtype, shape) -> Optional[bytes]:
    """The exact ``np.save`` v1 prefix (magic + header-length + dict) every
    cell of a fixed ``(dtype, shape)`` column shares, or ``None`` when the
    writer would not emit the machine-generated v1 form this plan pins.

    Built by running the actual writer on an empty-strided dummy (no
    guessing at numpy's dict formatting across versions) and re-verified
    through the same strict parser the host fast path trusts."""
    dtype = np.dtype(dtype)
    if dtype.hasobject:
        return None
    buf = io.BytesIO()
    try:
        np.save(buf, np.zeros(tuple(shape), dtype=dtype))
    except (TypeError, ValueError):
        return None
    raw = buf.getvalue()
    parsed = _parse_fast_npy_header(memoryview(raw))
    if parsed is None:
        return None
    parsed_dtype, parsed_shape, header_end = parsed
    if parsed_dtype != dtype or parsed_shape != tuple(shape):
        return None
    return raw[:header_end]


def plan_for_field(field) -> Tuple[Optional[DeviceColumnPlan], Optional[str]]:
    """``(plan, None)`` when ``field`` is device-decodable, else
    ``(None, reason)``. The codec owns the eligibility verdict
    (``device_decode_unsupported_reason``); this wrapper builds the pinned
    header for the eligible ones."""
    codec = field.codec
    if codec is None:
        return None, 'native arrow column (no codec payload to strip)'
    check = getattr(codec, 'device_decode_unsupported_reason', None)
    if check is None:
        return None, 'codec {} has no device-decode path'.format(
            type(codec).__name__)
    reason = check(field)
    if reason:
        return None, reason
    dtype = np.dtype(field.numpy_dtype)
    if dtype.itemsize == 8 and not jax_x64_enabled():
        return None, '8-byte dtype {} decodes as its 32-bit cousin without ' \
            'jax x64 mode (set JAX_ENABLE_X64 to plan it)'.format(dtype)
    header = npy_header_bytes(dtype, field.shape)
    if header is None:
        return None, 'np.save header for {} {} is not the machine-' \
            'generated v1 form'.format(dtype, field.shape)
    return DeviceColumnPlan(name=field.name, descr=dtype.str,
                            shape=tuple(field.shape), header=header), None


def plan_device_decode(schema, enabled: Optional[bool] = None,
                       has_predicate: bool = False,
                       has_ngram: bool = False,
                       decode_hints: Optional[dict] = None,
                       transform_spec=None,
                       transformed_schema=None,
                       batched_output: bool = True,
                       tolerant_decode: bool = False,
                       worker_supported: bool = True):
    """``(plans, declined)`` for a reader's output view: ``plans`` maps
    column name -> :class:`DeviceColumnPlan`; ``declined`` maps column
    name (or ``'*'`` for whole-reader reasons) -> human-readable reason.

    Whole-reader decliners come first — features that need decoded host
    values make every column ineligible: predicates evaluate on decoded
    cells, NGram regroups decoded rows, a host ``TransformSpec`` receives
    decoded columns (a ``device=True`` spec instead *fuses into* the
    jitted decode), and row-granular readers split columns into per-row
    views the raw grid cannot satisfy."""
    declined: Dict[str, str] = {}
    if enabled is None:
        enabled = device_decode_enabled()
    if not enabled:
        return {}, {'*': '{}=off'.format(DEVICE_DECODE_ENV_VAR)}
    if not batched_decode_enabled():
        # the per-cell A/B switch demands every codec cell go through the
        # host per-cell loop; bytes-through would silently bypass it
        return {}, {'*': '{}=off forces the host per-cell loop'.format(
            BATCHED_DECODE_ENV_VAR)}
    if not batched_output:
        return {}, {'*': 'row-granular reader (rows split out of columns '
                         'before any loader could decode them)'}
    if not worker_supported:
        return {}, {'*': 'worker class has no bytes-through publish path '
                         '(supports_device_decode is unset)'}
    if has_predicate:
        return {}, {'*': 'predicate evaluates on decoded host values'}
    if has_ngram:
        return {}, {'*': 'NGram windows regroup decoded rows on the host'}
    if tolerant_decode:
        return {}, {'*': 'on_decode_error quarantines per-cell codec '
                         'failures, which only the host decode can observe'}
    if transform_spec is not None and not getattr(transform_spec, 'device',
                                                  False):
        return {}, {'*': 'host TransformSpec receives decoded columns '
                         '(declare device=True to fuse it into the jitted '
                         'decode instead)'}
    if (transform_spec is not None and transformed_schema is not None
            and set(transformed_schema.fields) != set(schema.fields)):
        # workers publish pre-transform columns under bytes-through; a
        # field-set-changing spec would break the batch namedtuple contract
        return {}, {'*': 'device TransformSpec changes the field set '
                         '(edit dtypes/shapes in place to stay fusable)'}
    if not jax_backend_available():
        return {}, {'*': 'no jax backend initializes on this host'}
    plans: Dict[str, DeviceColumnPlan] = {}
    hints = decode_hints or {}
    for name, field in schema.fields.items():
        if name in hints:
            declined[name] = 'per-field decode hint overrides the codec'
            continue
        plan, reason = plan_for_field(field)
        if plan is None:
            declined[name] = reason or 'ineligible'
        else:
            plans[name] = plan
    return plans, declined


# ---------------------------------------------------------------------------
# worker side: raw views + host repack
# ---------------------------------------------------------------------------

def raw_column_view(column, plan: DeviceColumnPlan) -> Optional[np.ndarray]:
    """The ``(n, stride)`` uint8 grid of one (large_)binary column's raw
    cells, zero-copy out of the arrow data buffer (single-chunk columns;
    multi-chunk concatenates), or ``None`` when the stored bytes do not
    match the plan's pinned layout — nulls, stride drift, any cell whose
    header differs from the pinned prefix. ``None`` means "host-decode and
    repack", never an error."""
    chunks = column.chunks if isinstance(column, pa.ChunkedArray) else [column]
    header = np.frombuffer(plan.header, dtype=np.uint8)
    stride = plan.stride
    parts = []
    for chunk in chunks:
        if chunk.null_count:
            return None
        n = len(chunk)
        if n == 0:
            continue
        offsets, data = split_binary_chunk(chunk)
        if int(offsets[1]) - int(offsets[0]) != stride or not bool(
                np.all(np.diff(offsets) == stride)):
            return None
        grid = data[int(offsets[0]):int(offsets[-1])].reshape(n, stride)
        if not bool((grid[:, :plan.header_len] == header).all()):
            return None
        parts.append(grid)
    if not parts:
        return np.empty((0, stride), dtype=np.uint8)
    if len(parts) == 1:
        return parts[0]
    return np.concatenate(parts, axis=0)


def repack_to_raw(plan: DeviceColumnPlan, decoded) -> np.ndarray:
    """Host-decoded ``(n, *shape)`` values re-laid as the plan's raw
    ``(n, stride)`` grid — the uniform-representation fallback when
    :func:`raw_column_view` declines a chunk (and the ETL repack primitive
    for ``CompressedNdarrayCodec`` stores, ``etl/repack.py``)."""
    decoded = np.ascontiguousarray(decoded, dtype=plan.dtype)
    n = decoded.shape[0] if decoded.ndim else 0
    if decoded.shape[1:] != plan.shape:
        raise ValueError('repack_to_raw: column {!r} decoded to {} but the '
                         'plan pins cell shape {}'.format(
                             plan.name, decoded.shape[1:], plan.shape))
    out = np.empty((n, plan.stride), dtype=np.uint8)
    out[:, :plan.header_len] = np.frombuffer(plan.header, dtype=np.uint8)
    if plan.cell_nbytes:
        out[:, plan.header_len:] = decoded.reshape(n, -1).view(np.uint8)
    return out


# ---------------------------------------------------------------------------
# decode: numpy reference + jitted device path
# ---------------------------------------------------------------------------

def decode_raw_host(plan: DeviceColumnPlan, raw) -> np.ndarray:
    """Bit-identical numpy reference for the jitted decoder, and the host
    fallback when no loader claims a bytes-through reader's raw columns.
    Returns a WRITABLE ``(n, *shape)`` array, matching the per-cell path's
    contract."""
    raw = np.asarray(raw)
    n = raw.shape[0]
    if not plan.cell_count:
        return np.empty((n,) + plan.shape, dtype=plan.dtype)
    payload = np.ascontiguousarray(raw[:, plan.header_len:])
    if not payload.flags.writeable:
        payload = payload.copy()
    return payload.view(plan.dtype).reshape((n,) + plan.shape)


def decode_raw_jax(plan: DeviceColumnPlan, raw):
    """One planned column's jittable decode: header-strip + bitcast +
    reshape. ``raw`` is a ``(n, stride)`` uint8 array (jnp or np); the
    result is the ``(n, *shape)`` typed array, bit-identical to
    :func:`decode_raw_host` (little-endian descrs only — big-endian is
    excluded at plan time)."""
    import jax
    import jax.numpy as jnp
    n = raw.shape[0]
    dtype = plan.dtype
    if not plan.cell_count:
        return jnp.zeros((n,) + plan.shape, dtype=dtype)
    payload = raw[:, plan.header_len:]
    if dtype.kind == 'b':
        # np.save stores bools as 0x00/0x01; nonzero-is-True matches the
        # numpy buffer-view semantics exactly for those values
        out = payload != 0
    elif dtype.itemsize == 1:
        out = jax.lax.bitcast_convert_type(payload, dtype)
    else:
        out = jax.lax.bitcast_convert_type(
            payload.reshape(n, plan.cell_count, dtype.itemsize), dtype)
    return out.reshape((n,) + plan.shape)


def build_fused_infeed(plans: Dict[str, DeviceColumnPlan],
                       transform_spec=None):
    """ONE jitted program for the staging stream: decode every planned raw
    column, then apply the device-flagged ``TransformSpec`` over the full
    column dict. The returned callable takes and returns a dict of
    device-compatible arrays (the caller keeps host-only columns out and
    merges them back; ``stage_to_global`` / ``prefetch_to_device`` /
    ``JaxDataLoader`` all share this builder so the three call sites
    cannot drift)."""
    import jax
    plans = dict(plans)
    func = None
    if transform_spec is not None and getattr(transform_spec, 'func',
                                              None) is not None:
        func = transform_spec.func

    def _fused(columns):
        out = dict(columns)
        for name, plan in plans.items():
            if name in out:
                out[name] = decode_raw_jax(plan, out[name])
        if func is not None:
            out = func(out)
        return out

    return jax.jit(_fused)


def split_device_columns(batch, plans: Dict[str, DeviceColumnPlan],
                         include_unplanned: bool = False):
    """``(device_cols, host_cols)``: planned raw columns go through the
    jitted program; every other column stays a host numpy array, untouched
    — a bytes-through batch must not silently turn unplanned columns into
    immutable ``jax.Array``s (consumers mutate batches in place).
    ``include_unplanned=True`` additionally routes unplanned numeric
    ndarrays through the jit — required when a fused device
    ``TransformSpec`` runs, since its func receives the full column dict;
    object/str columns stay on the host either way."""
    device_cols, host_cols = {}, {}
    for name, value in batch.items():
        if name in plans:
            device_cols[name] = value
        elif (include_unplanned and isinstance(value, np.ndarray)
              and value.dtype.kind in 'biufc'):
            device_cols[name] = value
        else:
            host_cols[name] = value
    return device_cols, host_cols
