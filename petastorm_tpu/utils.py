"""Small shared helpers used across reader/worker modules.

Reference analogue: ``petastorm/utils.py`` (its ``decode_row`` lives on
``unischema.decode_row`` here; this module holds cross-cutting value casts).
"""

from __future__ import annotations

import os

import numpy as np

_FALSY_STRINGS = frozenset(('false', '0', '', 'no'))


def atomic_write(path: str, write_fn) -> str:
    """Write a text artifact atomically: ``write_fn(file)`` runs against a
    sibling tmp file that is ``os.replace``d over ``path`` only on success,
    and never outlives a failed write. A crash mid-dump — exactly when
    diagnostic artifacts (chrome traces, flight records, ``.prom`` files)
    matter most — can neither leave truncated output that tooling rejects
    nor clobber a previous good artifact at the same path."""
    tmp = '{}.tmp.{}'.format(path, os.getpid())
    try:
        with open(tmp, 'w') as f:
            write_fn(f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def parse_bool_string(value: str) -> bool:
    """Parse a hive-partition-style boolean string. ``bool('False')`` is True in
    python, which silently inverts ``flag=False`` partitions — hence this."""
    return value.strip().lower() not in _FALSY_STRINGS


def cast_partition_value(numpy_dtype, value: str):
    """Cast a hive partition directory value (always a string on disk) to the
    schema field's dtype. Single source of truth for partition-value coercion
    (used by the reader's partition-predicate pruning, the row worker, and the
    batch worker)."""
    if numpy_dtype is None or numpy_dtype is str:
        return value
    if numpy_dtype is bytes:
        return value.encode('utf-8')
    dtype = np.dtype(numpy_dtype)
    if dtype.kind == 'b':
        return np.bool_(parse_bool_string(value))
    return dtype.type(value)


def cast_string_to_type(target_type, value: str):
    """Cast a string to ``type(filter_value)`` for filter comparison, with
    correct bool semantics."""
    if target_type is bool:
        return parse_bool_string(value)
    return target_type(value)


def reassert_cpu_platform():
    """Re-assert ``jax_platforms='cpu'`` at config level when the environment
    asks for CPU.

    Environments that register accelerator plugins from ``sitecustomize`` may
    call ``jax.config.update('jax_platforms', ...)`` at interpreter startup,
    which takes precedence over the ``JAX_PLATFORMS`` env var — silently
    moving "CPU" runs onto real hardware (bf16 matmul defaults, shared chip).
    Call this after setting ``JAX_PLATFORMS=cpu``; no-op otherwise so an
    explicit accelerator selection still reaches hardware.
    """
    import os
    if os.environ.get('JAX_PLATFORMS') != 'cpu':
        return
    try:
        import jax
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
