"""User-supplied row/batch transforms executed on reader workers.

Reference parity: ``petastorm/transform.py`` — ``TransformSpec`` (:27-57),
``transform_schema`` (:60-89).

TPU-first addition: a ``TransformSpec`` may declare ``is_batched_jax=True``; the
JAX adapter (``petastorm_tpu/jax_utils``) will then run ``func`` on-device under
``jax.jit`` over whole batches instead of on the CPU worker.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec:
    """Defines a transform applied on a worker (thread/process) before data
    reaches the consumer, plus the schema mutation it implies.

    :param func: callable applied to each row dict (``make_reader``) or pandas
        DataFrame (``make_batch_reader``). May be ``None`` if only field
        selection/removal is needed.
    :param edit_fields: list of :class:`UnischemaField` (or 4-tuples
        ``(name, dtype, shape, nullable)``) added/modified by the transform.
    :param removed_fields: field names deleted by the transform.
    :param selected_fields: if set, the post-transform schema keeps exactly these
        fields. Mutually exclusive with ``removed_fields``
        (reference ``transform.py:53-57``).
    :param device: declare ``func`` jit-compatible (jnp ops over a dict of
        batch columns, no Python side effects). A device spec is **fused
        into the jitted device-decode program** on the staging stream
        (``ops.decode.build_fused_infeed``) instead of running on CPU
        workers — the ``is_batched_jax`` promise above, made real. When the
        reader's columns are not device-eligible (``docs/decode.md``), a
        device spec still runs on the host over the same columnar dict
        (jnp ops accept numpy arrays), so results do not depend on
        eligibility.
    """

    def __init__(self, func: Optional[Callable] = None,
                 edit_fields: Optional[List] = None,
                 removed_fields: Optional[List[str]] = None,
                 selected_fields: Optional[List[str]] = None,
                 device: bool = False):
        self.func = func
        self.device = bool(device)
        self.edit_fields = [self._as_field(f) for f in (edit_fields or [])]
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        if self.selected_fields is not None and self.removed_fields:
            raise ValueError('Only one of removed_fields and selected_fields can be specified')

    @staticmethod
    def _as_field(f):
        if isinstance(f, UnischemaField):
            return f
        name, dtype, shape, nullable = f
        return UnischemaField(name, dtype, shape, None, nullable)


def transform_schema(schema: Unischema, transform_spec: TransformSpec) -> Unischema:
    """Derive the post-transform :class:`Unischema`
    (reference ``transform.py:60-89``)."""
    removed = set(transform_spec.removed_fields)
    unknown = removed - set(schema.fields.keys())
    if unknown:
        raise ValueError('removed_fields names unknown fields: {}'.format(sorted(unknown)))
    fields = {name: field for name, field in schema.fields.items() if name not in removed}
    for edited in transform_spec.edit_fields:
        fields[edited.name] = edited
    if transform_spec.selected_fields is not None:
        unknown = set(transform_spec.selected_fields) - set(fields.keys())
        if unknown:
            raise ValueError('selected_fields names unknown fields: {}'.format(sorted(unknown)))
        fields = {name: field for name, field in fields.items()
                  if name in transform_spec.selected_fields}
    return Unischema(schema._name + '_transformed', list(fields.values()))


def apply_columnar_transform(transform_spec: TransformSpec,
                             transformed_schema: Unischema, columns):
    """The columnar transform contract, shared by the streaming columnar
    worker and the indexed loader: ``func`` receives a dict of column arrays;
    the result is filtered to the transformed schema's fields."""
    if transform_spec.func is not None:
        columns = transform_spec.func(columns)
    return {name: columns[name] for name in transformed_schema.fields
            if name in columns}
