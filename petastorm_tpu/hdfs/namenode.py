"""HDFS namenode resolution from Hadoop configs + HA failover wrapper.

Reference parity: ``petastorm/hdfs/namenode.py`` —
``HdfsNamenodeResolver`` parses ``hdfs-site.xml``/``core-site.xml`` found via
``HADOOP_HOME``/``HADOOP_PREFIX``/``HADOOP_INSTALL`` (:34-128);
``failover_all_class_methods`` wraps every public method of a connected
filesystem with round-robin namenode retry (:146-208);
``HdfsConnector.connect_to_either_namenode`` (:241-319).

The underlying client here is ``fsspec``'s hadoop filesystem
(pyarrow libhdfs under the hood) instead of the deprecated
``pyarrow.hdfs`` API.
"""

from __future__ import annotations

import functools
import logging
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

MAX_FAILOVER_ATTEMPTS = 2


class HdfsConnectError(IOError):
    pass


class MaxFailoversExceeded(RuntimeError):
    def __init__(self, failed_exceptions, max_failover_attempts, func_name):
        self.failed_exceptions = failed_exceptions
        self.max_failover_attempts = max_failover_attempts
        self.__name__ = func_name
        super(MaxFailoversExceeded, self).__init__(
            'Failover attempts exceeded maximum ({}) for function {}; '
            'exceptions: {}'.format(max_failover_attempts, func_name,
                                    failed_exceptions))


class HdfsNamenodeResolver(object):
    """Resolves HDFS name services to lists of namenode host:port pairs from
    Hadoop XML configuration."""

    def __init__(self, hadoop_configuration: Optional[Dict] = None):
        self._hadoop_env = None
        self._hadoop_path = None
        if hadoop_configuration is None:
            hadoop_configuration = self._load_site_configs()
        self._config = hadoop_configuration or {}

    def _load_site_configs(self) -> Dict[str, str]:
        """Locate and parse core-site.xml + hdfs-site.xml (reference :45-83)."""
        config: Dict[str, str] = {}
        for env in ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL'):
            path = os.environ.get(env)
            if not path:
                continue
            conf_dir = os.path.join(path, 'etc', 'hadoop')
            if not os.path.isdir(conf_dir):
                continue
            self._hadoop_env, self._hadoop_path = env, path
            for fname in ('core-site.xml', 'hdfs-site.xml'):
                fpath = os.path.join(conf_dir, fname)
                if os.path.exists(fpath):
                    config.update(self._parse_xml(fpath))
            break
        return config

    @staticmethod
    def _parse_xml(path: str) -> Dict[str, str]:
        out = {}
        try:
            root = ET.parse(path).getroot()
        except ET.ParseError as e:
            logger.warning('Could not parse %s: %s', path, e)
            return out
        for prop in root.iter('property'):
            name = prop.findtext('name')
            value = prop.findtext('value')
            if name is not None and value is not None:
                out[name] = value
        return out

    def resolve_hdfs_name_service(self, namespace: str) -> Optional[List[str]]:
        """Name service → list of namenode 'host:port' (reference :84-118);
        None when the namespace is not a configured name service."""
        namenodes = self._config.get('dfs.ha.namenodes.' + namespace)
        if not namenodes:
            return None
        hosts = []
        for nn in namenodes.split(','):
            address = self._config.get(
                'dfs.namenode.rpc-address.{}.{}'.format(namespace, nn.strip()))
            if address:
                hosts.append(address)
        if not hosts:
            raise HdfsConnectError(
                'Name service {} has namenode ids {} but no rpc-addresses '
                'configured'.format(namespace, namenodes))
        return hosts

    def resolve_default_hdfs_service(self) -> List:
        """[nameservice, [namenodes]] from fs.defaultFS (reference :119-128)."""
        default_fs = self._config.get('fs.defaultFS', '')
        if not default_fs.startswith('hdfs://'):
            raise HdfsConnectError(
                'Unable to determine namenode: fs.defaultFS={!r}'.format(default_fs))
        nameservice = default_fs[len('hdfs://'):].split('/')[0]
        namenodes = self.resolve_hdfs_name_service(nameservice)
        if namenodes is None:
            namenodes = [nameservice]   # direct host(:port), not a nameservice
        return [nameservice, namenodes]


# OSError subclasses that describe the *request*, not the connection: a
# missing file must surface as FileNotFoundError, not trigger namenode
# reconnects and MaxFailoversExceeded (the reference only fails over on
# connection-type ArrowIOError, namenode.py:181).
_NON_FAILOVER_ERRORS = (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError, FileExistsError)


#: Failover backoff/budget (shared RetryPolicy shape; see
#: ``docs/robustness.md``). Jittered backoff between reconnect attempts
#: keeps a fleet of readers from hammering a recovering namenode in
#: lockstep; the wall budget bounds how long one call can chase failovers.
FAILOVER_BACKOFF_S = 0.1
FAILOVER_TOTAL_BUDGET_S = 60.0


def _failover_classify(exc: BaseException) -> str:
    """Failover classification: request-shaped errors surface immediately;
    connection-shaped ``OSError``/``IOError`` rotate namenodes."""
    from petastorm_tpu import resilience
    if isinstance(exc, _NON_FAILOVER_ERRORS):
        return resilience.PERMANENT
    if isinstance(exc, (IOError, OSError)):
        return resilience.TRANSIENT
    return resilience.PERMANENT


def namenode_failover(func):
    """Retry a filesystem method across namenodes on connection errors
    (reference ``namenode_failover`` decorator, :146-186), driven by the
    shared :class:`petastorm_tpu.resilience.RetryPolicy` — which adds the
    full-jitter backoff between reconnects and the total-wall cap the old
    fixed loop lacked (many readers failing over together must decorrelate,
    not storm the surviving namenode)."""
    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        from petastorm_tpu.resilience import RetryPolicy
        failures = []

        def rotate(exc, _attempt):
            failures.append(exc)
            self._try_next_namenode()

        policy = RetryPolicy(attempts=MAX_FAILOVER_ATTEMPTS + 1,
                             initial_backoff_s=FAILOVER_BACKOFF_S,
                             total_budget_s=FAILOVER_TOTAL_BUDGET_S,
                             classify=_failover_classify)
        try:
            return policy.call(func, self, *args, on_retry=rotate,
                               description=getattr(func, '__name__',
                                                   str(func)), **kwargs)
        except _NON_FAILOVER_ERRORS:
            raise
        except (IOError, OSError) as e:
            failures.append(e)
            raise MaxFailoversExceeded(failures, MAX_FAILOVER_ATTEMPTS,
                                       getattr(func, '__name__', str(func)))
    return wrapper


class HAHdfsClient(object):
    """Wraps a connected hadoop filesystem, reconnecting to the next namenode
    in round-robin order whenever a call raises a connection error
    (reference ``HAHdfsClient`` + ``failover_all_class_methods``, :189-319)."""

    _PROXY_METHODS = ('open', 'ls', 'find', 'info', 'exists', 'makedirs',
                      'rm', 'mv', 'cp_file', 'created', 'modified', 'isdir',
                      'isfile', 'du', 'glob')

    def __init__(self, connector_cls, namenodes: List[str]):
        self._connector_cls = connector_cls
        self._namenodes = list(namenodes)
        # Connect to whichever namenode answers first ('either namenode',
        # reference :275-290) — the first listed may be the standby/down one.
        errors = []
        for i, host_port in enumerate(self._namenodes):
            try:
                self._fs = self._connect(host_port)
                self._index = i
                break
            except (IOError, OSError) as e:
                errors.append(e)
        else:
            raise HdfsConnectError(
                'Could not connect to any namenode of {}: {}'.format(
                    self._namenodes, errors))

    def _connect(self, host_port: str):
        return self._connector_cls(host_port)

    def _try_next_namenode(self):
        """Rotate to the next reachable namenode; when none answers, keep the
        current handle so the retry loop (not a raw connect error) decides when
        to give up."""
        for _ in range(len(self._namenodes)):
            self._index = (self._index + 1) % len(self._namenodes)
            candidate = self._namenodes[self._index]
            logger.warning('Failing over to namenode %s', candidate)
            try:
                self._fs = self._connect(candidate)
                return
            except (IOError, OSError) as e:
                logger.warning('Namenode %s unreachable: %s', candidate, e)

    def __getattr__(self, name):
        if name in self._PROXY_METHODS:
            method = getattr(type(self._fs), name, None)
            if method is None:
                # fall through to plain delegation for fs-specific helpers
                return getattr(self._fs, name)

            @namenode_failover
            def call(self, *args, **kwargs):
                return getattr(self._fs, name)(*args, **kwargs)
            return call.__get__(self, type(self))
        return getattr(self._fs, name)


class HdfsConnector(object):
    """Connect to (HA) HDFS via fsspec/pyarrow (reference :241-319)."""

    MAX_NAMENODES = 2

    @classmethod
    def hdfs_connect_namenode(cls, host_port: str):
        import fsspec
        host, _, port = host_port.partition(':')
        # skip_instance_cache: a failover reconnect must get a FRESH client,
        # not fsspec's cached (possibly wedged) instance for the same args.
        return fsspec.filesystem('hdfs', host=host or 'default',
                                 port=int(port) if port else 8020,
                                 skip_instance_cache=True)

    @classmethod
    def connect_to_either_namenode(cls, namenodes: List[str]):
        """Return an :class:`HAHdfsClient` over up to MAX_NAMENODES namenodes."""
        return HAHdfsClient(cls.hdfs_connect_namenode,
                            namenodes[:cls.MAX_NAMENODES])
