"""HDFS namenode resolution + HA failover (reference ``petastorm/hdfs/``)."""
