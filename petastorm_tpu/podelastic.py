"""Elastic pod membership: host-death/join shard rebalancing with a
machine-checked exactly-once certificate.

PR 15 made a single host's worker pool elastic (crashed workers respawn and
their in-flight items re-ventilate exactly once, fenced by
``lineage.delivery_deficit``). This module lifts that contract one level:
**hosts within a pod**. A host that dies mid-epoch loses its shard leases to
the survivors; a host that joins triggers a bounded rebalance; in both cases
the receiving host resumes the shard from checkpointable cursor state, and a
machine-checked certificate proves that every row of the epoch was delivered
exactly once *across the membership change* — a row whose data landed before
the death is never re-delivered, a row in flight is never lost.

Substrate
---------
The pod coordinates through a **shared coordination directory**
(``coord_root``) — the same substrate the shared row-group cache already
requires of a pod (one filesystem every host mounts). The alternative
substrate (the podobs/peer-cache HTTP plane) is deliberately NOT a fallback:
a pod configured with peers but no coordination directory gets a loud
:class:`ElasticConfigError`, never a silent downgrade to
heartbeats-over-HTTP with different failure semantics.

Every publication into the directory is atomic (``utils.atomic_write`` —
tmp + ``os.replace``) and the one *fencing* write — the per-batch delivery
record — is an ``os.link`` claim: write the record to a tmp file, link it to
its final name, and let ``FileExistsError`` mean "another host already
delivered this batch". The link either exists with complete content or does
not exist; there is no observable intermediate state, so the claim is the
pod-level analogue of the worker plane's delivery-deficit fence.

Liveness without wall clocks
----------------------------
Member records carry a monotonically increasing ``beats`` counter, never a
timestamp (cross-host wall clocks are not comparable and petalint R2 bans
them here). An observer tracks, per peer, the last counter value it saw and
how many of its *own* beats have passed since that value advanced: a host is
dead when it failed to advance within ``ttl_beats`` observer beats — the
``health.py`` monotonic-heartbeat idiom (progress, not timestamps) applied
across processes. Because liveness is counter-relative, a simulated pod
stepping K hosts round-robin in one process is exactly as deterministic as a
real pod beating on a cadence.

Leases and rebalancing
----------------------
The row-group index is partitioned into ``num_leases`` contiguous piece
ranges (:class:`LeasePlan`). Assignment is **rendezvous (HRW) hashing**
(:func:`rendezvous_assign`): lease *i* belongs to the live host with the
highest ``md5(lease:host)`` score. Rendezvous hashing makes the rebalance
*bounded by construction*: when a host dies, exactly its leases move (every
other lease keeps its argmax); when a host joins, exactly the leases the new
host wins move. No coordinator, no election — every host computes the same
assignment from the same sorted live set.

Exactly-once across the rebalance
---------------------------------
Each lease has a deterministic batch grid: a (seed, epoch, lease)-keyed
permutation of the lease's rows sliced into fixed batches — any holder
computes bit-identical batch content (the ``indexed.py`` pure-function
design, applied per lease). Delivery of batch *b* is the atomic creation of
its claim record; the lease's cursor checkpoint is published *after* the
claim. A takeover therefore resumes at
``max(checkpointed cursor, max(claimed batch) + 1)``: the claim scan covers
the crash window between a claim and its cursor flush (never re-deliver),
while an unclaimed in-flight batch is simply re-produced by the new holder
(never lost). :class:`ElasticCoverageAuditor` machine-checks the result the
way ``CoverageAuditor.assert_complete`` does, naming every duplicate or
dropped batch by host + parquet path + row group, and **refuses to certify a
partial pod** (a required host whose records cannot be read is a named
problem, never a silently shrunk denominator).

Kill switch
-----------
Everything is default-off. With no ``elastic=`` config the import creates no
files and no threads; with :data:`ELASTIC_ENV_VAR` explicitly ``0`` even an
explicit config is refused loudly. Nothing in this module ever spawns a
thread — hosts are driven by their callers (a training loop, the CI
simulator, the benchmark), so the kill-switch assertion is structural.

See ``docs/robustness.md`` (fault model, proof sketch) and
``docs/troubleshooting.md`` ("a host died mid-training").
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: Environment knob for the elastic plane. Default OFF: elasticity only arms
#: when a reader/loader is handed an explicit ``elastic=`` config AND this
#: variable is not an explicit ``0``/``false``/``off`` (the kill switch wins
#: over code).
ELASTIC_ENV_VAR = 'PETASTORM_TPU_ELASTIC'

#: Subdirectories of the coordination root.
MEMBERS_DIR = 'members'
LEASES_DIR = 'leases'
DELIVERED_DIR = 'delivered'

#: Schema version stamped into every coordination record.
RECORD_VERSION = 1

#: Default liveness window, in observer beats (see module docstring).
DEFAULT_TTL_BEATS = 3

#: ReaderStats counters the elastic plane feeds (also merged pod-wide by
#: ``podobs.PodObserver``).
ELASTIC_COUNTERS = ('hosts_joined', 'hosts_died', 'leases_rebalanced',
                    'rows_resumed')


class ElasticConfigError(ValueError):
    """A pod-elasticity misconfiguration that must fail loudly at
    construction (most importantly: expecting elasticity without a shared
    coordination directory — the HTTP observability plane is NOT a
    substrate fallback)."""


class SimulatedHostDeath(SystemExit):
    """An injected whole-host death (chaos scenario ``host-death``).
    ``SystemExit`` like :class:`~petastorm_tpu.faultfs.SimulatedWorkerCrash`:
    no ``except Exception`` on the delivery path may swallow it — in a real
    pod the interpreter exits and the survivors see the heartbeat stop."""


def elastic_killed() -> bool:
    """True when :data:`ELASTIC_ENV_VAR` explicitly disables the plane."""
    return os.environ.get(ELASTIC_ENV_VAR, '').strip().lower() in (
        '0', 'false', 'off')


def default_host_id() -> str:
    """``hostname-pid``: unique per participating process (a pod of K
    simulated hosts in one process passes explicit ids instead)."""
    return '{}-{}'.format(socket.gethostname(), os.getpid())


def _read_json(path: str) -> Optional[dict]:
    """Load one coordination record; ``None`` for missing records. All
    publications are atomic, so a readable file is a complete record — a
    torn/unparsable one is a real error and raises."""
    try:
        with open(path, 'r') as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except ValueError as e:
        raise ElasticConfigError(
            'corrupt coordination record {}: {} (records are published '
            'atomically; a torn file means the coord_root filesystem does '
            'not honor rename atomicity)'.format(path, e))


def rendezvous_assign(num_leases: int,
                      hosts: Sequence[str]) -> Dict[int, str]:
    """Highest-random-weight (rendezvous) assignment: lease ``i`` goes to
    ``argmax_h md5('{i}:{h}')`` over the live hosts. A pure function of
    (num_leases, set(hosts)) — every host computes the same map — and
    *minimally disruptive*: adding or removing one host moves only the
    leases whose argmax changed (exactly that host's leases)."""
    if not hosts:
        return {}
    assignment = {}
    for lease in range(num_leases):
        best_host, best_score = None, b''
        for host in sorted(set(hosts)):
            score = hashlib.md5('{}:{}'.format(lease, host).encode()).digest()
            if best_host is None or score > best_score:
                best_host, best_score = host, score
        assignment[lease] = best_host
    return assignment


class PodMembership:
    """Host registration + counter-based liveness over the coordination
    directory.

    Each member publishes ``members/<host_id>.json`` carrying a
    monotonically increasing ``beats`` counter (:meth:`beat`). Observers
    (:meth:`observe`) judge liveness purely from counter *progress* relative
    to their own beat count — no wall clocks anywhere (petalint R2 scope).
    """

    def __init__(self, coord_root: str, host_id: Optional[str] = None,
                 ttl_beats: int = DEFAULT_TTL_BEATS):
        if not coord_root:
            raise ElasticConfigError(
                'pod elasticity needs coord_root: a directory shared by '
                'every host (the same substrate the shared cache uses). '
                'The podobs/peer HTTP plane is an observability surface, '
                'NOT a membership substrate — configuring peers without a '
                'coord_root is an error, never a fallback')
        if ttl_beats < 1:
            raise ElasticConfigError('ttl_beats must be >= 1, got '
                                     '{!r}'.format(ttl_beats))
        self.coord_root = os.path.abspath(coord_root)
        self.host_id = host_id or default_host_id()
        self.ttl_beats = int(ttl_beats)
        self._members_dir = os.path.join(self.coord_root, MEMBERS_DIR)
        os.makedirs(self._members_dir, exist_ok=True)
        self.beats = 0
        #: per-peer progress clock: host -> [last_counter, my_beats_when_it
        #: last_advanced] (observer-local, never persisted)
        self._progress: Dict[str, List[int]] = {}
        #: hosts currently judged live (after the last :meth:`observe`)
        self._live: Tuple[str, ...] = ()
        #: monotonic per-observer membership-transition tallies
        self.counters = {'hosts_joined': 0, 'hosts_died': 0}
        self.beat()

    def _member_path(self, host_id: str) -> str:
        return os.path.join(self._members_dir, host_id + '.json')

    def beat(self) -> int:
        """Publish one heartbeat (atomic replace of the member record) and
        return the new counter value."""
        from petastorm_tpu.utils import atomic_write
        self.beats += 1
        record = {'host': self.host_id, 'beats': self.beats,
                  'pid': os.getpid(), 'version': RECORD_VERSION}
        atomic_write(self._member_path(self.host_id),
                     lambda f: json.dump(record, f))
        return self.beats

    def leave(self) -> None:
        """Graceful departure: remove the member record (survivors see the
        host vanish immediately instead of waiting out ``ttl_beats``)."""
        try:
            os.remove(self._member_path(self.host_id))
        except FileNotFoundError:
            pass

    def observe(self) -> Tuple[str, ...]:
        """Read every member record, advance the progress clocks, and return
        the sorted live host set. Tallies joins (a host never seen before
        goes live) and deaths (a live host stalls past ``ttl_beats`` of this
        observer's own beats, or its record vanished) into
        :attr:`counters`."""
        records = {}
        try:
            names = os.listdir(self._members_dir)
        except FileNotFoundError:
            names = []
        for name in sorted(names):
            if not name.endswith('.json'):
                continue
            record = _read_json(os.path.join(self._members_dir, name))
            if record is not None:
                records[record.get('host', name[:-5])] = record
        previously_live = set(self._live)
        known = set(self._progress)
        live = []
        for host, record in sorted(records.items()):
            counter = int(record.get('beats', 0))
            clock = self._progress.get(host)
            if clock is None:
                self._progress[host] = [counter, self.beats]
                live.append(host)
                continue
            if counter > clock[0]:
                clock[0], clock[1] = counter, self.beats
            if self.beats - clock[1] <= self.ttl_beats:
                live.append(host)
        for host in live:
            # a join is any dead->live (or never-seen->live) transition:
            # first sight, or a declared-dead host whose counter resumed
            if host not in previously_live and (host not in known
                                                or host in records):
                if host not in previously_live:
                    self.counters['hosts_joined'] += 1
        for host in sorted(previously_live.difference(live)):
            self.counters['hosts_died'] += 1
            logger.warning('pod member %s is dead (no heartbeat progress '
                           'within %d observer beats)', host, self.ttl_beats)
        self._live = tuple(live)
        return self._live

    @property
    def live_hosts(self) -> Tuple[str, ...]:
        """The live set as of the last :meth:`observe`."""
        return self._live


class LeasePlan:
    """Partition of the row-group index into ``num_leases`` contiguous piece
    ranges, each with its own deterministic (seed, epoch, lease) batch grid.

    A lease's batch stream is a pure function of (dataset, seed, epoch,
    lease): any holder — original or takeover — computes bit-identical
    batches. ``drop_last`` semantics apply per lease (deterministic
    addressing needs a fixed grid; the tail rows rotate in via the next
    epoch's permutation, exactly like ``IndexedBatchLoader``)."""

    def __init__(self, row_offsets: np.ndarray, batch_size: int,
                 num_leases: int, seed: int = 0, shuffle: bool = True):
        n_pieces = len(row_offsets) - 1
        if num_leases < 1:
            raise ElasticConfigError('num_leases must be >= 1, got '
                                     '{!r}'.format(num_leases))
        if num_leases > n_pieces:
            raise ElasticConfigError(
                'num_leases {} exceeds the {} row groups of the dataset — '
                'a lease needs at least one row group'.format(num_leases,
                                                              n_pieces))
        if batch_size < 1:
            raise ElasticConfigError('batch_size must be >= 1, got '
                                     '{!r}'.format(batch_size))
        self.row_offsets = np.asarray(row_offsets, np.int64)
        self.batch_size = int(batch_size)
        self.num_leases = int(num_leases)
        self.seed = seed
        self.shuffle = shuffle
        # contiguous piece partition, remainder spread over the first leases
        base, extra = divmod(n_pieces, num_leases)
        bounds = [0]
        for i in range(num_leases):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        #: lease i covers pieces [piece_bounds[i], piece_bounds[i+1])
        self.piece_bounds = bounds

    def lease_pieces(self, lease: int) -> range:
        return range(self.piece_bounds[lease], self.piece_bounds[lease + 1])

    def lease_rows(self, lease: int) -> Tuple[int, int]:
        """Global row span [start, stop) of ``lease``."""
        lo, hi = self.piece_bounds[lease], self.piece_bounds[lease + 1]
        return int(self.row_offsets[lo]), int(self.row_offsets[hi])

    def batches_per_lease(self, lease: int) -> int:
        start, stop = self.lease_rows(lease)
        return (stop - start) // self.batch_size

    def total_batches(self) -> int:
        return sum(self.batches_per_lease(lease)
                   for lease in range(self.num_leases))

    def batch_rows(self, lease: int, epoch: int, batch: int) -> np.ndarray:
        """Global row indices of batch ``batch`` of ``lease`` in ``epoch`` —
        the pure addressing function every holder shares."""
        start, stop = self.lease_rows(lease)
        n = stop - start
        if self.shuffle:
            rng = np.random.default_rng((self.seed, epoch, lease))
            perm = rng.permutation(n)
        else:
            perm = np.arange(n, dtype=np.int64)
        window = perm[batch * self.batch_size:(batch + 1) * self.batch_size]
        return (np.asarray(window, np.int64) + start)

    def describe(self) -> dict:
        return {'num_leases': self.num_leases,
                'batch_size': self.batch_size,
                'total_batches': self.total_batches(),
                'piece_bounds': list(self.piece_bounds),
                'seed': self.seed, 'shuffle': self.shuffle}


class LeaseLedger:
    """Lease cursors + fenced delivery records in the coordination
    directory.

    - ``leases/lease_<i>.json``: the holder + next-batch cursor, republished
      (atomic replace) after each delivery.
    - ``delivered/l<i>_e<e>_b<b>.json``: THE delivery fence. Created with
      write-tmp-then-``os.link`` so creation is atomic-with-content;
      ``FileExistsError`` means another host (usually the dead previous
      holder) already delivered the batch and the caller must skip it.
    """

    def __init__(self, coord_root: str):
        self.coord_root = os.path.abspath(coord_root)
        self._leases_dir = os.path.join(self.coord_root, LEASES_DIR)
        self._delivered_dir = os.path.join(self.coord_root, DELIVERED_DIR)
        os.makedirs(self._leases_dir, exist_ok=True)
        os.makedirs(self._delivered_dir, exist_ok=True)

    # -- lease cursors ---------------------------------------------------------

    def _lease_path(self, lease: int) -> str:
        return os.path.join(self._leases_dir, 'lease_{}.json'.format(lease))

    def read_lease(self, lease: int) -> Optional[dict]:
        return _read_json(self._lease_path(lease))

    def checkpoint_lease(self, lease: int, holder: str, epoch: int,
                         next_batch: int) -> None:
        """Publish the lease cursor (atomic replace). Runs AFTER the delivery
        claim: the claim is the fence, the cursor is an optimization the
        takeover scan can always repair."""
        from petastorm_tpu.utils import atomic_write
        record = {'lease': lease, 'holder': holder,
                  'cursor': {'epoch': epoch, 'batch': next_batch},
                  'version': RECORD_VERSION}
        atomic_write(self._lease_path(lease),
                     lambda f: json.dump(record, f))

    # -- the delivery fence ----------------------------------------------------

    def _delivery_path(self, lease: int, epoch: int, batch: int) -> str:
        return os.path.join(
            self._delivered_dir,
            'l{}_e{}_b{}.json'.format(lease, epoch, batch))

    def claim_delivery(self, lease: int, epoch: int, batch: int,
                       host: str, rows: int,
                       row_groups: Sequence[dict]) -> bool:
        """Atomically claim delivery of one (lease, epoch, batch). True =
        this caller owns the delivery (it may hand the batch to the
        consumer); False = already delivered by someone else (skip — this is
        the never-redeliver half of the exactly-once contract)."""
        final = self._delivery_path(lease, epoch, batch)
        tmp = '{}.tmp.{}.{}'.format(final, os.getpid(), host)
        record = {'lease': lease, 'epoch': epoch, 'batch': batch,
                  'host': host, 'rows': int(rows),
                  'row_groups': list(row_groups),
                  'version': RECORD_VERSION}
        try:
            with open(tmp, 'w') as f:
                json.dump(record, f)
            try:
                os.link(tmp, final)
            except FileExistsError:
                return False
            return True
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass

    def read_delivery(self, lease: int, epoch: int,
                      batch: int) -> Optional[dict]:
        return _read_json(self._delivery_path(lease, epoch, batch))

    def delivered_batches(self, lease: int, epoch: int) -> List[int]:
        """Batch indices of every claimed delivery of (lease, epoch)."""
        prefix = 'l{}_e{}_b'.format(lease, epoch)
        out = []
        try:
            names = os.listdir(self._delivered_dir)
        except FileNotFoundError:
            return out
        for name in names:
            if name.startswith(prefix) and name.endswith('.json'):
                try:
                    out.append(int(name[len(prefix):-5]))
                except ValueError:
                    continue
        return sorted(out)

    def resume_batch(self, lease: int, epoch: int) -> int:
        """Where a takeover resumes ``lease``:
        ``max(checkpointed cursor, max(claimed batch) + 1)``. The claim scan
        covers the window between a dead host's last claim and its never-
        flushed cursor — the `delivery_deficit` rule at pod level: claimed
        means delivered, so never re-deliver; unclaimed means in flight, so
        re-produce."""
        cursor = 0
        record = self.read_lease(lease)
        if record is not None:
            stored = record.get('cursor') or {}
            if int(stored.get('epoch', -1)) == epoch:
                cursor = int(stored.get('batch', 0))
        claimed = self.delivered_batches(lease, epoch)
        if claimed:
            cursor = max(cursor, max(claimed) + 1)
        return cursor


class ElasticCoverageAuditor:
    """Machine-check pod-level exactly-once delivery for one epoch from the
    ledger's claim records — the ``CoverageAuditor`` contract lifted to the
    pod: every (lease, batch) of the plan's grid claimed exactly once, every
    problem named by host + parquet path + row group, and a **partial pod
    refuses to certify** (``require_hosts`` that never appear in the member
    directory make the denominator unknowable)."""

    def __init__(self, plan: LeasePlan, ledger: LeaseLedger,
                 pieces: Optional[Sequence] = None):
        self.plan = plan
        self.ledger = ledger
        #: dataset pieces (``IndexedDatasetReader.pieces``) for naming
        #: dropped batches by path + row group even when no record exists
        self.pieces = pieces

    def _name_lease(self, lease: int) -> str:
        if not self.pieces:
            return 'lease {} (pieces {}..{})'.format(
                lease, self.plan.piece_bounds[lease],
                self.plan.piece_bounds[lease + 1] - 1)
        briefs = []
        for piece_index in self.plan.lease_pieces(lease):
            piece = self.pieces[piece_index]
            briefs.append('{}#rg{}'.format(
                os.path.basename(getattr(piece, 'path', '?')),
                getattr(piece, 'row_group', '?')))
        return 'lease {} [{}]'.format(lease, ', '.join(briefs))

    def audit_epoch(self, epoch: int,
                    require_hosts: Sequence[str] = ()) -> dict:
        """``{'expected_batches', 'delivered_batches', 'duplicates',
        'missing', 'by_host', 'unreachable', 'ok', 'problems'}`` for one
        epoch. ``require_hosts`` arms the partial-pod refusal: any named
        host with no member record is reported and fails certification."""
        problems: List[str] = []
        unreachable: List[str] = []
        members_dir = os.path.join(self.ledger.coord_root, MEMBERS_DIR)
        for host in require_hosts:
            path = os.path.join(members_dir, str(host) + '.json')
            if _read_json(path) is None:
                unreachable.append(str(host))
        if unreachable:
            problems.append(
                'partial_pod: required host(s) {} have no member record — '
                'their deliveries cannot be attributed, so the certificate '
                'denominator is incomplete; refusing to certify'.format(
                    ', '.join(unreachable)))
        expected = 0
        delivered = 0
        duplicates: List[str] = []
        missing: List[str] = []
        by_host: Dict[str, int] = {}
        for lease in range(self.plan.num_leases):
            grid = self.plan.batches_per_lease(lease)
            expected += grid
            claimed = self.ledger.delivered_batches(lease, epoch)
            claimed_set = set(claimed)
            for batch in claimed:
                record = self.ledger.read_delivery(lease, epoch, batch) or {}
                host = str(record.get('host', '?'))
                by_host[host] = by_host.get(host, 0) + 1
                if batch >= grid:
                    duplicates.append(
                        'host {} delivered out-of-grid batch {} of {} '
                        '(grid has {} batches): {}'.format(
                            host, batch, self._name_lease(lease), grid,
                            self._describe_record(record)))
            delivered += len(claimed_set.intersection(range(grid)))
            for batch in range(grid):
                if batch not in claimed_set:
                    missing.append(
                        'batch {} of {} was never delivered (dropped '
                        'rows)'.format(batch, self._name_lease(lease)))
        # the os.link fence makes same-batch duplicates structurally
        # impossible (one claim file per grid point); what CAN go wrong is
        # an out-of-grid claim (checked above) or a drop (missing)
        if duplicates:
            problems.append('{} duplicate/forged delivery record(s): {}'
                            .format(len(duplicates), '; '.join(duplicates)))
        if missing:
            problems.append('{} dropped batch(es): {}'.format(
                len(missing), '; '.join(missing)))
        ok = not problems and not unreachable
        return {'epoch': epoch, 'expected_batches': expected,
                'delivered_batches': delivered,
                'duplicates': duplicates, 'missing': missing,
                'by_host': by_host, 'unreachable': unreachable,
                'checked': True, 'ok': ok, 'problems': problems}

    @staticmethod
    def _describe_record(record: dict) -> str:
        groups = record.get('row_groups') or []
        return ', '.join('{}#rg{}'.format(os.path.basename(
            str(g.get('path', '?'))), g.get('row_group', '?'))
            for g in groups) or '<no row groups recorded>'

    def assert_complete(self, epoch: int,
                        require_hosts: Sequence[str] = ()) -> dict:
        """Raise :class:`podobs.PodCertificateError` naming every problem
        when the epoch's delivery is not provably exactly-once."""
        audit = self.audit_epoch(epoch, require_hosts=require_hosts)
        if not audit['ok']:
            from petastorm_tpu.podobs import PodCertificateError
            raise PodCertificateError(
                'pod exactly-once certificate failed for epoch {}: {}'
                .format(epoch, '; '.join(audit['problems'])))
        return audit


class ElasticHost:
    """One pod member's delivery loop over its held leases.

    Driven entirely by its caller (``step()``/``run_epoch()``) — this class
    never spawns a thread, so the module-level kill-switch guarantee (no
    files, no threads unless explicitly armed) holds structurally. Batches
    are produced from the shared :class:`LeasePlan` grid through an
    ``IndexedDatasetReader``, fenced through the :class:`LeaseLedger`, and
    handed to ``on_batch`` (the consumer) only when the claim succeeded.
    """

    def __init__(self, dataset, plan: LeasePlan,
                 membership: PodMembership, ledger: LeaseLedger,
                 stats=None, host_index: int = 0,
                 checkpoint_every: int = 8):
        if checkpoint_every < 1:
            raise ElasticConfigError(
                'checkpoint_every must be >= 1, got {}'.format(
                    checkpoint_every))
        #: cursor-checkpoint cadence. The delivery CLAIM is the recovery
        #: authority (resume_batch takes max(cursor, claims + 1)); the
        #: cursor is a hint that bounds the takeover's claim scan, so
        #: persisting it every batch buys nothing but an extra fsync-path
        #: write on the hot loop.
        self.checkpoint_every = checkpoint_every
        self.dataset = dataset
        self.plan = plan
        self.membership = membership
        self.ledger = ledger
        self.host_id = membership.host_id
        #: stable index for deterministic chaos targeting (the simulator's
        #: creation order; a real pod may pass jax.process_index())
        self.host_index = host_index
        self.stats = stats
        self.counters = {name: 0 for name in ELASTIC_COUNTERS}
        self.counters['batches_delivered'] = 0
        self.counters['batches_skipped_claimed'] = 0
        self._held: Tuple[int, ...] = ()
        self._cursors: Dict[int, int] = {}
        self._epoch = 0
        self.dead = False

    # -- accounting ------------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        if self.stats is not None:
            self.stats.add(name, n)

    def elastic_snapshot(self) -> dict:
        """The per-host ``elastic`` section ``podobs.make_observe_fn``
        serves: transition counters + the current lease view."""
        snap = dict(self.counters)
        snap.update(self.membership.counters)
        snap['held_leases'] = len(self._held)
        snap['expected_batches'] = self.plan.total_batches()
        snap['epoch'] = self._epoch
        return snap

    # -- membership + rebalance ------------------------------------------------

    def rebalance(self, epoch: int) -> Tuple[int, ...]:
        """Observe the live set, recompute the rendezvous assignment, and
        adopt newly won leases from their checkpointed/claimed resume
        points. Returns the held lease tuple."""
        joined_before = self.membership.counters['hosts_joined']
        died_before = self.membership.counters['hosts_died']
        live = self.membership.observe()
        if self.stats is not None:
            self.stats.add('hosts_joined',
                           self.membership.counters['hosts_joined']
                           - joined_before)
            self.stats.add('hosts_died',
                           self.membership.counters['hosts_died']
                           - died_before)
        assignment = rendezvous_assign(self.plan.num_leases, live)
        held = tuple(sorted(lease for lease, host in assignment.items()
                            if host == self.host_id))
        initial = not self._held and not self._cursors
        for lease in held:
            if lease in self._cursors:
                continue
            resume = self.ledger.resume_batch(lease, epoch)
            self._cursors[lease] = resume
            if not initial or resume > 0:
                # a takeover — an established host winning a lease it did
                # not hold, or a mid-epoch joiner adopting a lease with
                # prior progress: count the rebalance and the rows whose
                # delivery this host resumed responsibility for
                self._count('leases_rebalanced')
                remaining = self.plan.batches_per_lease(lease) - resume
                self._count('rows_resumed',
                            max(0, remaining) * self.plan.batch_size)
                logger.warning(
                    'host %s took over lease %d at batch %d of epoch %d '
                    '(%d batches remain)', self.host_id, lease, resume,
                    epoch, max(0, remaining))
        for lease in set(self._cursors).difference(held):
            # lease lost to a rebalance (a joining host won it): drop the
            # local cursor; the new holder resumes from the ledger
            del self._cursors[lease]
        self._held = held
        self._epoch = epoch
        return held

    # -- delivery --------------------------------------------------------------

    def _chaos_step(self) -> None:
        from petastorm_tpu.faultfs import chaos_from_env
        injector = chaos_from_env()
        if injector is not None and injector.should_kill_host(
                self.host_index, self.counters['batches_delivered']):
            self.dead = True
            raise SimulatedHostDeath(
                'chaos: injected death of host {} (index {}) after {} '
                'delivered batches (seed {})'.format(
                    self.host_id, self.host_index,
                    self.counters['batches_delivered'], injector.seed))

    def step(self, epoch: int, on_batch=None) -> Optional[Tuple[int, int]]:
        """Deliver at most one batch: pick the held lease with the most
        remaining work, produce its next grid batch, claim it, and (when the
        claim won) assemble the rows and hand them to ``on_batch``. Returns
        the delivered (lease, batch), or ``None`` when this host's leases
        are drained."""
        if self.dead:
            raise SimulatedHostDeath('host {} is dead'.format(self.host_id))
        self._chaos_step()
        self.membership.beat()
        candidates = [
            (self.plan.batches_per_lease(lease) - self._cursors[lease],
             -lease)
            for lease in self._held
            if self._cursors[lease] < self.plan.batches_per_lease(lease)]
        if not candidates:
            return None
        remaining, neg_lease = max(candidates)
        lease = -neg_lease
        batch = self._cursors[lease]
        rows = self.plan.batch_rows(lease, epoch, batch)
        groups = self._row_groups_of(rows)
        claimed = self.ledger.claim_delivery(
            lease, epoch, batch, self.host_id, len(rows), groups)
        if claimed:
            if on_batch is not None:
                on_batch(self.dataset.gather(rows), lease, batch)
            self._count('batches_delivered')
        else:
            # the previous holder's delivery landed before it died: the
            # exactly-once fence says skip, never re-deliver
            self._count('batches_skipped_claimed')
        cursor = self._cursors[lease] = batch + 1
        drained = cursor >= self.plan.batches_per_lease(lease)
        if drained or cursor % self.checkpoint_every == 0:
            self.ledger.checkpoint_lease(lease, self.host_id, epoch, cursor)
        return lease, batch

    def _row_groups_of(self, rows: np.ndarray) -> List[dict]:
        piece_ids = np.unique(np.searchsorted(
            self.dataset.row_offsets, rows, side='right') - 1)
        out = []
        for piece_index in piece_ids:
            piece = self.dataset.pieces[int(piece_index)]
            out.append({'path': getattr(piece, 'path', '?'),
                        'row_group': getattr(piece, 'row_group', -1)})
        return out

    def remaining(self) -> int:
        return sum(self.plan.batches_per_lease(lease) - self._cursors[lease]
                   for lease in self._held)


class ElasticPodSim:
    """K simulated hosts over one coordination directory — the CI/benchmark
    harness that makes pod elasticity testable on one machine.

    Hosts are stepped round-robin (deterministic: the same seed and chaos
    spec replay the identical rebalance and the identical injected tallies).
    The ``host-death``/``host-join`` chaos scenarios
    (:data:`~petastorm_tpu.faultfs.CHAOS_ENV_VAR`) inject membership
    transitions mid-epoch; the epoch completes when every lease's grid is
    claimed, and :meth:`certificate` machine-checks exactly-once delivery
    across whatever rebalances happened."""

    def __init__(self, dataset, coord_root: str, k_hosts: int,
                 batch_size: int, num_leases: Optional[int] = None,
                 seed: int = 0, shuffle: bool = True,
                 ttl_beats: int = DEFAULT_TTL_BEATS, stats=None):
        if elastic_killed():
            raise ElasticConfigError(
                'pod elasticity is disabled ({}=0): the kill switch wins '
                'over code; unset it to run an elastic pod'.format(
                    ELASTIC_ENV_VAR))
        if k_hosts < 1:
            raise ElasticConfigError('k_hosts must be >= 1, got '
                                     '{!r}'.format(k_hosts))
        self.dataset = dataset
        self.coord_root = os.path.abspath(coord_root)
        self.k_hosts = int(k_hosts)
        if num_leases is None:
            num_leases = min(len(dataset.pieces), 2 * k_hosts)
        self.plan = LeasePlan(dataset.row_offsets, batch_size, num_leases,
                              seed=seed, shuffle=shuffle)
        self.ledger = LeaseLedger(coord_root)
        self.ttl_beats = ttl_beats
        self.stats = stats
        self.hosts: List[ElasticHost] = []
        self.deaths: List[str] = []
        self.joins: List[str] = []
        for index in range(k_hosts):
            self._spawn_host(index)

    def _spawn_host(self, index: int) -> ElasticHost:
        membership = PodMembership(
            self.coord_root, host_id='host-{}'.format(index),
            ttl_beats=self.ttl_beats)
        host = ElasticHost(self.dataset, self.plan, membership, self.ledger,
                           stats=self.stats, host_index=index)
        self.hosts.append(host)
        return host

    def auditor(self) -> ElasticCoverageAuditor:
        return ElasticCoverageAuditor(self.plan, self.ledger,
                                      pieces=self.dataset.pieces)

    def _maybe_join(self, total_delivered: int) -> Optional[ElasticHost]:
        from petastorm_tpu.faultfs import chaos_from_env
        injector = chaos_from_env()
        if injector is None or not injector.should_join_host(
                total_delivered):
            return None
        host = self._spawn_host(len(self.hosts))
        self.joins.append(host.host_id)
        logger.warning('chaos: host %s joined the pod after %d delivered '
                       'batches', host.host_id, total_delivered)
        return host

    def run_epoch(self, epoch: int = 0, on_batch=None) -> dict:
        """Drive the pod through one epoch (round-robin host steps,
        rebalancing on every membership transition) and return the run
        report. Raises ``RuntimeError`` if the surviving hosts cannot
        complete the grid (e.g. every host died)."""
        for host in self.hosts:
            host.rebalance(epoch)
        total = self.plan.total_batches()
        delivered = 0
        stall_rounds = 0
        while delivered < total:
            survivors = [h for h in self.hosts if not h.dead]
            if not survivors:
                raise RuntimeError(
                    'every pod host died; {}/{} batches delivered'.format(
                        delivered, total))
            progressed = False
            membership_changed = False
            for host in list(survivors):
                try:
                    result = host.step(epoch, on_batch=on_batch)
                except SimulatedHostDeath:
                    self.deaths.append(host.host_id)
                    membership_changed = True
                    continue
                if result is not None:
                    progressed = True
            # a claim IS a delivery (the fence is the delivery record), so
            # the pod-wide count is the sum of per-host claim counters —
            # dead hosts' pre-death claims included. Scanning delivered/
            # here would be O(batches^2) over the epoch.
            delivered = sum(h.counters['batches_delivered']
                            for h in self.hosts)
            if self._maybe_join(delivered) is not None:
                membership_changed = True
            if membership_changed or not progressed:
                # survivors re-observe: dead hosts age out after ttl_beats
                # of counter silence, joiners appear, leases rebalance
                for host in self.hosts:
                    if not host.dead:
                        host.rebalance(epoch)
            if not progressed:
                stall_rounds += 1
                if stall_rounds > self.ttl_beats + 2:
                    raise RuntimeError(
                        'elastic pod wedged: {}/{} batches delivered and '
                        'no survivor can make progress'.format(delivered,
                                                               total))
            else:
                stall_rounds = 0
        return self.report(epoch)

    def report(self, epoch: int = 0) -> dict:
        counters: Dict[str, int] = {}
        for host in self.hosts:
            for name, value in host.elastic_snapshot().items():
                if name in ('expected_batches', 'epoch', 'held_leases'):
                    continue
                counters[name] = counters.get(name, 0) + value
        return {'kind': 'petastorm_tpu.elastic_pod_report',
                'version': RECORD_VERSION,
                'epoch': epoch,
                'plan': self.plan.describe(),
                'hosts': [h.host_id for h in self.hosts],
                'deaths': list(self.deaths),
                'joins': list(self.joins),
                'counters': counters,
                'audit': self.auditor().audit_epoch(epoch)}

    def certificate(self, epoch: int = 0,
                    require_hosts: Sequence[str] = ()) -> dict:
        """Machine-check exactly-once delivery across the epoch's
        rebalances (raises ``PodCertificateError`` on any problem)."""
        return self.auditor().assert_complete(epoch,
                                              require_hosts=require_hosts)

    def close(self) -> None:
        for host in self.hosts:
            host.membership.leave()


def resolve_elastic_shard(elastic, cur_shard, shard_count,
                          shard_by_jax_process):
    """Reader-factory integration: when an ``elastic=`` config is given (and
    the kill switch allows), shard assignment becomes **lease-driven** — the
    factory joins the pod's membership plane and derives
    ``(cur_shard, shard_count)`` from this host's position in the live set.

    ``elastic`` is a dict: ``coord_root`` (required — see
    :class:`ElasticConfigError`), optional ``host_id`` and ``ttl_beats``.
    Mutually exclusive with explicit ``cur_shard``/``shard_count`` and with
    ``shard_by_jax_process`` (one source of shard truth). Returns
    ``(cur_shard, shard_count, membership-or-None)``.

    This is a *static* snapshot for the streaming readers (their ventilation
    schedule is fixed at construction); the fully elastic mid-epoch
    rebalance lives in the lease-grid plane (:class:`ElasticHost` /
    :class:`ElasticPodSim`) over the indexed loaders. The snapshot still
    buys pod-membership-driven sharding: a restarted reader on a resized pod
    picks up the new shard map with no coordinator."""
    if elastic is None:
        return cur_shard, shard_count, None
    if elastic_killed():
        logger.warning('elastic= requested but %s=0: the kill switch wins; '
                       'no membership files or shard override created',
                       ELASTIC_ENV_VAR)
        return cur_shard, shard_count, None
    if cur_shard is not None or shard_count is not None:
        raise ElasticConfigError(
            'elastic= is mutually exclusive with explicit '
            'cur_shard/shard_count (lease-driven sharding IS the shard '
            'assignment)')
    if shard_by_jax_process:
        raise ElasticConfigError(
            'elastic= is mutually exclusive with shard_by_jax_process '
            '(pick one source of shard truth)')
    if not isinstance(elastic, dict):
        raise ElasticConfigError(
            "elastic= must be a dict like {'coord_root': ...}, got "
            '{!r}'.format(elastic))
    unknown = set(elastic) - {'coord_root', 'host_id', 'ttl_beats'}
    if unknown:
        raise ElasticConfigError(
            'unknown elastic= option(s) {}; valid: coord_root, host_id, '
            'ttl_beats'.format(sorted(unknown)))
    membership = PodMembership(elastic.get('coord_root'),
                               host_id=elastic.get('host_id'),
                               ttl_beats=elastic.get('ttl_beats',
                                                     DEFAULT_TTL_BEATS))
    live = membership.observe()
    if membership.host_id not in live:
        raise ElasticConfigError(
            'host {} did not appear in its own membership observation — '
            'the coord_root {} is not behaving like a shared directory'
            .format(membership.host_id, membership.coord_root))
    index = live.index(membership.host_id)
    logger.info('elastic shard assignment: host %s is shard %d of %d '
                '(coord_root %s)', membership.host_id, index, len(live),
                membership.coord_root)
    return index, len(live), membership
