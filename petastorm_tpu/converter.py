"""Dataset converter: materialize-and-cache in-memory data for training loops.

Reference parity: ``petastorm/spark/spark_dataset_converter.py`` — but the
input is a pyarrow Table or pandas DataFrame instead of a Spark DataFrame
(a Spark DataFrame is accepted too when pyspark is importable: it is collected
to arrow via ``toPandas``). Feature mapping:

- parent cache dir conf (``:59-78``)        → ``set_parent_cache_dir_url`` /
  ``PETASTORM_TPU_CACHE_DIR`` env var / explicit argument
- query-plan cache key (``:476-512``)       → content fingerprint of the arrow
  table (schema + row count + per-column chunk hashes) + params
- precision normalization (``:524-544``)    → ``dtype_overrides`` / ``precision``
- uncompressed default (``:685-691``)       → same
- atexit best-effort delete (``:115-119``)  → same
- rank/size sanity warning (``:122-159``)   → ``jax.process_index/count`` first,
  then Horovod/MPI/PMI env vars
- ``make_tf_dataset``/``make_torch_dataloader`` (``:198,:246``) → plus
  ``make_jax_loader``
"""

from __future__ import annotations

import atexit
import hashlib
import logging
import os
import threading
import time
import uuid
import warnings
import weakref
from typing import Dict, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url

logger = logging.getLogger(__name__)

_parent_cache_dir_url = None
_cache_lock = threading.Lock()
# cache key -> SavedDataset; mirrors the reference's driver-side registry
_materialized: Dict[str, 'SavedDataset'] = {}


def set_parent_cache_dir_url(url: Optional[str]) -> None:
    """Set the parent directory under which converters materialize datasets
    (reference conf key ``petastorm.spark.converter.parentCacheDirUrl``)."""
    global _parent_cache_dir_url
    _parent_cache_dir_url = normalize_dir_url(url) if url else None


def _get_parent_cache_dir_url(explicit: Optional[str]) -> str:
    if explicit:
        return normalize_dir_url(explicit)
    if _parent_cache_dir_url:
        return _parent_cache_dir_url
    env = os.environ.get('PETASTORM_TPU_CACHE_DIR')
    if env:
        return normalize_dir_url(env)
    raise ValueError(
        'No cache directory configured. Pass parent_cache_dir_url=, call '
        'set_parent_cache_dir_url(), or set PETASTORM_TPU_CACHE_DIR')


def _get_rank_and_size():
    """(rank, size) of this training process: JAX process topology first, env
    vars second (reference ``_get_horovod_rank_and_size``, ``:122-135``)."""
    try:
        import jax
        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # jax absent or uninitialized distributed runtime
        pass
    for rank_env, size_env in [('HOROVOD_RANK', 'HOROVOD_SIZE'),
                               ('OMPI_COMM_WORLD_RANK', 'OMPI_COMM_WORLD_SIZE'),
                               ('PMI_RANK', 'PMI_SIZE')]:
        rank, size = os.environ.get(rank_env), os.environ.get(size_env)
        if rank is not None and size is not None:
            return int(rank), int(size)
    return None, None


def _check_rank_mismatch(cur_shard, shard_count):
    rank, size = _get_rank_and_size()
    if rank is not None and (cur_shard != rank or shard_count != size):
        warnings.warn('This process is rank {} of {} but cur_shard={} '
                      'shard_count={} were requested; double-check your '
                      'sharding arguments'.format(rank, size, cur_shard,
                                                  shard_count))


def _to_arrow_table(data) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    # Spark DataFrame (optional interop; collected to the driver). This is
    # deliberately single-machine — the framework never runs Spark jobs
    # (permanent decision, README "Migrating Spark pipelines"): the ceiling
    # is driver RAM (~2x the decoded dataset during conversion). Above it,
    # write parquet FROM Spark and read it with make_batch_reader directly.
    if hasattr(data, 'toPandas') and hasattr(data, 'schema'):
        logger.warning(
            'Collecting the Spark DataFrame to this machine for '
            'materialization (driver-RAM-bound; see README "Migrating '
            'Spark pipelines" for the cluster-write pattern)')
        return pa.Table.from_pandas(data.toPandas(), preserve_index=False)
    raise TypeError('Unsupported input type {}; expected pyarrow.Table, '
                    'pandas.DataFrame or pyspark DataFrame'.format(type(data)))


def _normalize_precision(table: pa.Table, precision: Optional[str]) -> pa.Table:
    """float64→float32 ('float32') or float32→float64 ('float64') column casts
    (reference ``_convert_precision``, ``:524-544``)."""
    if precision is None:
        return table
    if precision not in ('float32', 'float64'):
        raise ValueError("precision must be 'float32', 'float64' or None")
    src = pa.float64() if precision == 'float32' else pa.float32()
    dst = pa.float32() if precision == 'float32' else pa.float64()
    fields = []
    changed = False
    for f in table.schema:
        if f.type == src:
            fields.append(pa.field(f.name, dst, f.nullable))
            changed = True
        else:
            fields.append(f)
    return table.cast(pa.schema(fields)) if changed else table


#: id(table) → {params_repr: digest}; arrow tables are immutable, so a live
#: table object always re-hashes to the same digest and can be memoized by
#: identity. Keyed by id (pa.Table is weakref-able but not hashable) with a
#: finalizer evicting the entry when the table dies, so ids can't go stale.
_fingerprint_memo: Dict[int, Dict[str, str]] = {}


def _fingerprint_memo_for(table: pa.Table) -> Dict[str, str]:
    key = id(table)
    entry = _fingerprint_memo.get(key)
    if entry is None:
        entry = _fingerprint_memo[key] = {}
        weakref.finalize(table, _fingerprint_memo.pop, key, None)
    return entry


def _params_repr(params: Dict) -> str:
    """The one canonical serialization of materialization params — used both
    inside the content hash and as the memo key, which must stay in sync."""
    return repr(sorted(params.items()))


def _fingerprint(table: pa.Table, params: Dict) -> str:
    """Content-addressed cache key: schema + shape + ALL column bytes +
    materialization params.

    ALL data is hashed (not a prefix sample): two tables with identical
    prefixes but different later data must not collide, or a stale
    materialization would be silently reused. The data is streamed through
    Arrow IPC rather than hashing raw chunk buffers — a sliced table shares
    its parent's buffers, so raw-buffer hashing would collide slices at
    different offsets; IPC serializes exactly the logical region. Hashing is
    cheap relative to the parquet write it guards, but still O(data); repeat
    calls with the same live arrow table skip it via an identity memo at the
    caller (``make_dataset_converter``)."""
    params_repr = _params_repr(params)
    h = hashlib.sha256()
    h.update(table.schema.to_string().encode())
    h.update(str(table.num_rows).encode())

    class _HashSink:
        closed = False

        @staticmethod
        def write(data):
            h.update(data)
            return len(data)

    with pa.ipc.new_stream(_HashSink(), table.schema) as writer:
        writer.write_table(table)
    h.update(params_repr.encode())
    return h.hexdigest()[:32]


class SavedDataset(object):
    """Picklable handle to a materialized dataset (reference
    ``SparkDatasetConverter``, ``:162-187``): workers/other processes can
    unpickle it and open readers without re-materializing."""

    def __init__(self, cache_dir_url: str, file_urls, dataset_size: int,
                 parent_cache_dir_url: str):
        self.cache_dir_url = cache_dir_url
        self.file_urls = list(file_urls)
        self.dataset_size = dataset_size
        self._parent_cache_dir_url = parent_cache_dir_url

    def __len__(self):
        return self.dataset_size

    # -- consumption ---------------------------------------------------------

    def make_jax_loader(self, batch_size=32, mesh=None, num_epochs=None,
                        shuffling_queue_capacity=0, reader_pool_type='thread',
                        workers_count=4, cur_shard=None, shard_count=None,
                        **reader_kwargs):
        """Context manager yielding a :class:`JaxDataLoader` /
        :class:`ShardedJaxLoader` over the materialized data."""
        from petastorm_tpu.jax_utils import make_jax_loader
        from petastorm_tpu.reader import make_batch_reader
        if cur_shard is not None:
            _check_rank_mismatch(cur_shard, shard_count)
        reader = make_batch_reader(
            self.file_urls, num_epochs=num_epochs,
            reader_pool_type=reader_pool_type, workers_count=workers_count,
            cur_shard=cur_shard, shard_count=shard_count, **reader_kwargs)
        return make_jax_loader(reader, batch_size=batch_size, mesh=mesh,
                               shuffling_queue_capacity=shuffling_queue_capacity)

    def make_torch_dataloader(self, batch_size=32, num_epochs=None,
                              shuffling_queue_capacity=0,
                              reader_pool_type='thread', workers_count=4,
                              cur_shard=None, shard_count=None,
                              inmemory_cache_all=False, **reader_kwargs):
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        if cur_shard is not None:
            _check_rank_mismatch(cur_shard, shard_count)
        reader = make_batch_reader(
            self.file_urls, num_epochs=num_epochs,
            reader_pool_type=reader_pool_type, workers_count=workers_count,
            cur_shard=cur_shard, shard_count=shard_count, **reader_kwargs)
        return BatchedDataLoader(
            reader, batch_size=batch_size,
            shuffling_queue_capacity=shuffling_queue_capacity,
            inmemory_cache_all=inmemory_cache_all)

    def make_tf_dataset(self, batch_size=None, num_epochs=None,
                        reader_pool_type='thread', workers_count=4,
                        cur_shard=None, shard_count=None, **reader_kwargs):
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        if cur_shard is not None:
            _check_rank_mismatch(cur_shard, shard_count)
        reader = make_batch_reader(
            self.file_urls, num_epochs=num_epochs,
            reader_pool_type=reader_pool_type, workers_count=workers_count,
            cur_shard=cur_shard, shard_count=shard_count, **reader_kwargs)
        dataset = make_petastorm_dataset(reader)
        if batch_size:
            dataset = dataset.unbatch().batch(batch_size)
        return _TfDatasetContextManager(reader, dataset)

    # -- lifecycle ------------------------------------------------------------

    def delete(self):
        """Remove the materialized files (reference ``delete()``, ``:290-292``)."""
        fs, path, _ = get_filesystem_and_path_or_paths(self.cache_dir_url)
        try:
            if fs.exists(path):
                fs.rm(path, recursive=True)
        except OSError as e:
            logger.warning('Failed to delete %s: %s', self.cache_dir_url, e)
        with _cache_lock:
            for key, saved in list(_materialized.items()):
                if saved is self or saved.cache_dir_url == self.cache_dir_url:
                    del _materialized[key]


class _TfDatasetContextManager(object):
    def __init__(self, reader, dataset):
        self._reader = reader
        self.dataset = dataset

    def __enter__(self):
        return self.dataset

    def __exit__(self, exc_type, exc_val, exc_tb):
        self._reader.stop()
        self._reader.join()


def _wait_file_available(fs, paths, timeout_s: float = 30.0):
    """Poll until all paths exist (eventually-consistent object stores;
    reference ``_wait_file_available``, ``:592-621``)."""
    deadline = time.monotonic() + timeout_s
    pending = list(paths)
    while pending:
        pending = [p for p in pending if not fs.exists(p)]
        if not pending:
            return
        if time.monotonic() > deadline:
            raise RuntimeError('Timed out waiting for files: {}'.format(
                pending[:3]))
        time.sleep(0.1)


_MEDIAN_SIZE_WARN_BYTES = 50 * 1024 * 1024


def make_dataset_converter(data, parent_cache_dir_url: Optional[str] = None,
                           precision: Optional[str] = None,
                           compression: Optional[str] = None,
                           row_group_size_mb: float = 32.0,
                           delete_at_exit: bool = True) -> SavedDataset:
    """Materialize ``data`` to parquet under the cache dir (or reuse an
    existing materialization with identical content+params) and return a
    picklable :class:`SavedDataset` handle (reference ``make_spark_converter``,
    ``:646-706``)."""
    parent = _get_parent_cache_dir_url(parent_cache_dir_url)
    params = {'compression': compression or 'none',
              'row_group_size_mb': row_group_size_mb,
              'precision': precision or 'none'}
    # Memoize the O(data) fingerprint by identity of the ORIGINAL input, but
    # only for arrow tables — their API is immutable, so a live table object
    # always re-hashes to the same digest. pandas/Spark inputs are mutable
    # (a memo there could silently reuse a stale materialization after an
    # in-place edit), so they pay the full hash every call. Caveat: a table
    # built zero-copy over a numpy buffer that the caller then mutates
    # violates arrow's immutability contract and would stale-hit here —
    # exactly as it would corrupt any other arrow consumer of that table.
    memo = _fingerprint_memo_for(data) if isinstance(data, pa.Table) else None
    params_repr = _params_repr(params)
    key = memo.get(params_repr) if memo is not None else None
    table = None
    if key is None:
        table = _normalize_precision(_to_arrow_table(data), precision)
        key = _fingerprint(table, params)
        if memo is not None:
            memo[params_repr] = key

    with _cache_lock:
        cached = _materialized.get(key)
        if cached is not None:
            fs, path, _ = get_filesystem_and_path_or_paths(cached.cache_dir_url)
            if fs.exists(path):
                logger.info('Cache hit: reusing %s', cached.cache_dir_url)
                return cached
            del _materialized[key]

    if table is None:  # memo hit but no live materialization: convert now
        table = _normalize_precision(_to_arrow_table(data), precision)

    # cache dir name mirrors the reference's '{time}-appid-{appid}-{uuid}'
    dir_name = '{}-{}'.format(int(time.time()), uuid.uuid4().hex[:12])
    cache_dir_url = '{}/{}'.format(parent.rstrip('/'), dir_name)
    fs, path, _ = get_filesystem_and_path_or_paths(cache_dir_url)
    fs.makedirs(path, exist_ok=True)

    file_path = '{}/part_00000.parquet'.format(path)
    row_group_rows = max(
        1, int(row_group_size_mb * 1024 * 1024 /
               max(1, table.nbytes // max(1, table.num_rows))))
    with fs.open(file_path, 'wb') as f:
        pq.write_table(table, f, row_group_size=row_group_rows,
                       compression=compression or 'NONE')
    _wait_file_available(fs, [file_path])

    sizes = [fs.info(file_path)['size']]
    if np.median(sizes) > 0 and np.median(sizes) < 1024 and table.num_rows > 100000:
        warnings.warn('Materialized parquet files are very small; performance '
                      'may suffer (reference recommends >=50MB median)')

    # Scheme-less cache dirs (a bare path, which fs.py accepts) must yield
    # bare-path file urls — blindly prepending '<whole-path>://' produced
    # unopenable urls.
    if '://' in cache_dir_url:
        scheme = cache_dir_url.split('://', 1)[0]
        file_url = '{}://{}'.format(scheme, file_path)
    else:
        file_url = file_path
    saved = SavedDataset(cache_dir_url, [file_url], table.num_rows, parent)
    with _cache_lock:
        _materialized[key] = saved
    if delete_at_exit:
        atexit.register(_best_effort_delete, saved)
    return saved


def _best_effort_delete(saved: SavedDataset):
    try:
        saved.delete()
    except Exception:  # noqa: BLE001 — atexit must never raise
        pass
