"""pyarrow-style DNF ``filters`` for the reader: partition-value pruning,
row-group statistics pruning, and exact residual row filtering.

Reference parity: the reference hands ``filters`` straight to
``pq.ParquetDataset`` (``petastorm/reader.py:399-401``), which (pyarrow
>=0.17.1, ``setup.py:42``) prunes row groups by parquet column statistics for
any column and removes non-matching rows from scanned data. Here the same
semantics are built natively on the piece list:

1. **Planning time** — every conjunction is tested against each piece. Terms
   on hive partition columns evaluate *exactly* (a partition value is constant
   for the piece); terms on regular columns evaluate *conservatively* against
   the row-group min/max statistics from the file footer (a column with no
   statistics keeps the piece). A piece is pruned only when every conjunction
   is provably unsatisfiable for it.
2. **Worker time** — when any filter term names a non-partition column, the
   full DNF is pushed down as a row predicate so the output is row-exact, not
   just row-group-granular (matching modern pyarrow dataset semantics).

``filters`` grammar: ``[(col, op, val), ...]`` is a single AND conjunction; a
list of such lists is an OR of conjunctions. Ops: ``= == != < <= > >= in
not in``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow.parquet as pq

from petastorm_tpu.predicates import PredicateBase
from petastorm_tpu.utils import cast_partition_value, cast_string_to_type

FILTER_OPS = {
    '=': lambda a, b: a == b,
    '==': lambda a, b: a == b,
    '!=': lambda a, b: a != b,
    '<': lambda a, b: a < b,
    '<=': lambda a, b: a <= b,
    '>': lambda a, b: a > b,
    '>=': lambda a, b: a >= b,
    'in': lambda a, b: a in b,
    'not in': lambda a, b: a not in b,
}

Conjunction = List[Tuple[str, str, object]]


def normalize_filters(filters) -> Optional[List[Conjunction]]:
    """Validate ``filters`` and normalize to a list of conjunctions (DNF)."""
    if not filters:
        return None
    if isinstance(filters[0], tuple):
        raw_conjunctions = [list(filters)]
    else:
        raw_conjunctions = [list(c) for c in filters]
    conjunctions = []
    for raw in raw_conjunctions:
        if not raw:
            raise ValueError('filters contains an empty conjunction')
        conjunction: Conjunction = []
        for term in raw:
            if not (isinstance(term, tuple) and len(term) == 3):
                raise ValueError(
                    'filter terms must be (column, op, value) tuples; got '
                    '{!r}'.format(term))
            col, op, val = term
            if op not in FILTER_OPS:
                raise ValueError('Unsupported filter op {!r} on column {!r}; '
                                 'supported: {}'.format(op, col,
                                                        sorted(FILTER_OPS)))
            if op in ('in', 'not in'):
                if isinstance(val, (str, bytes)) \
                        or not hasattr(val, '__iter__'):
                    # a bare string is iterable but would evaluate with
                    # substring semantics at row time; any real collection
                    # (list, set, numpy array, range, ...) is fine
                    raise ValueError(
                        "filter ({!r}, {!r}, ...) needs a collection value; "
                        'got {!r}'.format(col, op, val))
                # materialize: the value is evaluated many times (per row in
                # workers, per row group at planning) — a one-shot iterator
                # would silently exhaust after the first evaluation. Prefer a
                # frozenset (O(1) membership per row; pickles cleanly);
                # unhashable elements fall back to a list.
                materialized = list(val)
                try:
                    val = frozenset(materialized)
                except TypeError:
                    val = materialized
            conjunction.append((col, op, val))
        conjunctions.append(conjunction)
    return conjunctions


def filter_column_names(conjunctions: Sequence[Conjunction]) -> List[str]:
    return sorted({col for conjunction in conjunctions
                   for col, _, _ in conjunction})


def _scalar_type_ok(dtype_kind: str, val) -> bool:
    if isinstance(val, bool):
        return dtype_kind == 'b'
    if isinstance(val, (int, float)):
        return dtype_kind in 'biuf'
    if isinstance(val, str):
        # a str value against a bytes ('S') column would compare str-vs-bytes
        # per row — always False, i.e. a silent zero-row result; surface the
        # mismatch here like the other type checks (pass bytes instead)
        return dtype_kind == 'U'
    if isinstance(val, bytes):
        return dtype_kind == 'S'
    return True                     # date/decimal/...: let the workers decide


def validate_filter_types(conjunctions: Sequence[Conjunction], schema,
                          partition_keys=()) -> None:
    """Reject obviously type-mismatched filter values at construction time.

    Without this, ``('id', '>', '5')`` on an int column would crash workers
    mid-iteration with a per-row ``TypeError`` (the reference's pyarrow path
    rejects it at dataset-open time). Partition columns are exempt — their
    string values coerce to the filter value's type."""
    for conjunction in conjunctions:
        for col, op, val in conjunction:
            if col in partition_keys:
                continue
            field = schema.fields.get(col)
            if field is None or field.numpy_dtype is None:
                continue
            try:
                kind = np.dtype(field.numpy_dtype).kind
            except TypeError:
                continue
            values = val if op in ('in', 'not in') else [val]
            try:
                iter(values)
            except TypeError:
                raise ValueError(
                    "filter ({!r}, {!r}, ...) needs an iterable value".format(
                        col, op))
            for v in values:
                if not _scalar_type_ok(kind, v):
                    raise ValueError(
                        'filter value {!r} is incompatible with column {!r} '
                        '(dtype kind {!r})'.format(v, col, kind))


def _eval_term(actual, op: str, val) -> bool:
    """Exact evaluation of one term on a concrete cell value. ``None`` /
    missing values fail every comparison (pyarrow null semantics)."""
    if actual is None:
        return False
    # hive partition values arrive as strings; coerce to the filter value's
    # type so ('id', '>', 5) works on an unregistered partition column. For
    # in/not-in the element type drives the coercion.
    if isinstance(actual, str):
        if op in ('in', 'not in'):
            ref = next(iter(val), None)
            if ref is not None and not isinstance(ref, str):
                actual = cast_string_to_type(type(ref), actual)
        elif not isinstance(val, str):
            actual = cast_string_to_type(type(val), actual)
    return bool(FILTER_OPS[op](actual, val))


class FiltersPredicate(PredicateBase):
    """Row-level DNF filter evaluation, pushed down to reader workers exactly
    like a user predicate. Rows failing every conjunction never leave the
    worker, making ``filters`` row-exact regardless of row-group layout."""

    def __init__(self, conjunctions: Sequence[Conjunction]):
        self._conjunctions = [list(c) for c in conjunctions]
        self._fields = filter_column_names(conjunctions)

    def get_fields(self) -> List[str]:
        return list(self._fields)

    def do_include(self, values: dict) -> bool:
        for conjunction in self._conjunctions:
            if all(_eval_term(values.get(col), op, val)
                   for col, op, val in conjunction):
                return True
        return False

    def specialize(self, piece, schema) -> Optional['FiltersPredicate']:
        """Resolve partition terms against the piece's constant partition
        values, so workers only ever evaluate real stored columns (partition
        columns may not even exist in the stored schema).

        Returns ``None`` when every row of the piece trivially passes (some
        conjunction is fully satisfied by partition values alone), else a
        predicate over the remaining non-partition terms. A piece where no
        conjunction survives yields a reject-all predicate — planning prunes
        such pieces, this is the defensive backstop."""
        partition_values = piece.partition_dict
        reduced: List[Conjunction] = []
        for conjunction in self._conjunctions:
            residual: Conjunction = []
            satisfiable = True
            for col, op, val in conjunction:
                if col in partition_values:
                    field = schema.fields.get(col)
                    actual = cast_partition_value(
                        field.numpy_dtype if field is not None else None,
                        partition_values[col])
                    if not _eval_term(actual, op, val):
                        satisfiable = False
                        break
                else:
                    residual.append((col, op, val))
            if not satisfiable:
                continue
            if not residual:
                return None     # conjunction holds for every row of the piece
            reduced.append(residual)
        return FiltersPredicate(reduced)


class RowGroupStatsEvaluator:
    """Conservative planning-time evaluation of DNF filters per row-group
    piece: partition terms exactly, regular-column terms against footer
    min/max statistics. Footer metadata is read lazily, once per file, and
    only when a filter actually names a non-partition column."""

    def __init__(self, filesystem, schema, preloaded_footers=None):
        self._fs = filesystem
        self._schema = schema
        # path -> (FileMetaData | None, {column path_in_schema: index})
        self._footers: Dict[str, Tuple[object, Dict[str, int]]] = {}
        # footers already parsed during row-group discovery (metadata-less
        # stores) — reuse instead of a second round-trip per file
        for path, md in (preloaded_footers or {}).items():
            columns = {md.schema.column(j).path: j
                       for j in range(md.num_columns)}
            self._footers[path] = (md, columns)

    # -- footer access ---------------------------------------------------------

    def prefetch_footers(self, paths, num_workers: int = 8) -> None:
        """Read the footers of ``paths`` concurrently (remote stores pay one
        round-trip per file; serial reads in the Reader constructor would
        dominate startup — mirrors ``load_row_groups``'s discovery pool)."""
        from concurrent.futures import ThreadPoolExecutor
        missing = sorted(set(paths) - set(self._footers))
        if not missing:
            return
        with ThreadPoolExecutor(max_workers=num_workers) as executor:
            for path, entry in zip(missing, executor.map(self._read_footer,
                                                         missing)):
                self._footers[path] = entry

    def _read_footer(self, path: str):
        try:
            with self._fs.open(path, 'rb') as f:
                md = pq.ParquetFile(f).metadata
            columns = {md.schema.column(j).path: j
                       for j in range(md.num_columns)}
            return md, columns
        except Exception:  # unreadable footer: never prune on its account
            return None, {}

    def _footer(self, path: str):
        if path not in self._footers:
            self._footers[path] = self._read_footer(path)
        return self._footers[path]

    def _column_stats(self, piece, col: str):
        """``(min, max, all_null)`` for the column chunk, or None when the
        statistics cannot support pruning."""
        md, columns = self._footer(piece.path)
        if md is None or col not in columns:
            return None
        if not 0 <= piece.row_group < md.num_row_groups:
            return None
        rg = md.row_group(piece.row_group)
        chunk = rg.column(columns[col])
        stats = chunk.statistics
        if stats is None:
            return None
        all_null = (stats.has_null_count and stats.null_count == rg.num_rows
                    and rg.num_rows > 0)
        if not stats.has_min_max:
            return (None, None, all_null) if all_null else None
        return stats.min, stats.max, all_null

    # -- term evaluation -------------------------------------------------------

    @staticmethod
    def _term_maybe_true(op: str, val, mn, mx, all_null: bool) -> bool:
        """Could *any* row of the chunk satisfy the term? False only when the
        statistics prove it cannot."""
        if all_null:
            return False            # null fails every supported op
        if mn is None or mx is None:
            return True
        try:
            if op in ('=', '=='):
                return mn <= val <= mx
            if op == '!=':
                return not (mn == mx == val)
            if op == '<':
                return mn < val
            if op == '<=':
                return mn <= val
            if op == '>':
                return mx > val
            if op == '>=':
                return mx >= val
            if op == 'in':
                return any(mn <= v <= mx for v in val)
            if op == 'not in':
                return not (mn == mx and mn in val)
        except TypeError:
            return True             # incomparable stats: keep the piece
        return True

    # -- piece evaluation ------------------------------------------------------

    def piece_maybe_matches(self, piece, conjunctions: Sequence[Conjunction],
                            partition_only: bool = False) -> bool:
        """True unless every conjunction is provably unsatisfiable for the
        piece. With ``partition_only`` no footer is touched: regular-column
        terms count as maybe-true — the cheap first pass that prunes on exact
        partition terms before any footer round-trips are paid."""
        partition_values = piece.partition_dict
        for conjunction in conjunctions:
            satisfiable = True
            for col, op, val in conjunction:
                if col in partition_values:
                    field = self._schema.fields.get(col)
                    actual = cast_partition_value(
                        field.numpy_dtype if field is not None else None,
                        partition_values[col])
                    # an uncastable partition value raises here: partition
                    # terms never reach the workers, so swallowing the error
                    # would silently disable the filter
                    if not _eval_term(actual, op, val):
                        satisfiable = False
                        break
                else:
                    if partition_only:
                        continue
                    stats = self._column_stats(piece, col)
                    if stats is None:
                        continue            # no statistics: cannot prune
                    mn, mx, all_null = stats
                    if not self._term_maybe_true(op, val, mn, mx, all_null):
                        satisfiable = False
                        break
            if satisfiable:
                return True
        return False
