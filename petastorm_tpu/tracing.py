"""Per-item pipeline tracing and continuous metrics emission.

``ReaderStats`` (PR 1-2) answers *how much* time each stage consumed in
aggregate; it cannot answer *which* item stalled *where*, or show how worker
decode, transport, device staging and the jitted train step interleave in
time. This module adds the span layer: a low-overhead, off-by-default
:class:`Tracer` holding a bounded ring buffer of spans that every component
on the sample path records into — ventilate, readahead, parquet read, decode,
serialize, result-queue wait, deserialize, host batching, device staging, and
the consumer's train step.

Design constraints:

- **Off by default, near-zero when off.** No ``Tracer`` object exists unless
  tracing was requested (``trace=`` kwarg or ``PETASTORM_TPU_TRACE``); call
  sites guard with ``if tracer is not None`` and workers behind a boolean, so
  the disabled path adds one attribute test per site.
- **Bounded memory.** Spans live in a ``deque(maxlen=capacity)``; long runs
  keep the most recent window and count what they dropped
  (:attr:`Tracer.dropped`) instead of growing without bound.
- **One clock across processes.** Span timestamps are
  ``time.perf_counter()`` values, which CPython maps to ``CLOCK_MONOTONIC``
  on Linux — a system-wide clock, so spans recorded inside spawned worker
  interpreters land on the same timeline as the consumer's without offset
  arithmetic. Workers ship their span batches back inside the existing
  per-item accounting control message (the ``merge_times`` pattern), each
  span stamped with the recording ``(pid, tid)`` so Perfetto renders one
  track per worker process/thread.
- **Perfetto-ready output.** :meth:`Tracer.export_chrome_trace` writes the
  Chrome trace-event JSON format (complete ``"ph": "X"`` events plus
  process/thread-name metadata), loadable in https://ui.perfetto.dev or
  ``chrome://tracing``.

Spans are plain tuples ``(name, cat, start_s, dur_s, pid, tid, args)`` —
cheap to record, cheap to pickle across the process-pool boundary.

:class:`MetricsEmitter` is the counters-side companion: a background thread
snapshotting a ``ReaderStats`` every N seconds to JSON-lines or Prometheus
text-exposition format, so a training job's infeed health is scrapable
without touching the training loop.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, List, Optional, Tuple

#: Environment variable controlling tracing when the ``trace=`` kwarg is left
#: at its default. ``''``/``'0'``/``'false'``/``'off'`` disable;
#: ``'1'``/``'true'``/``'on'`` enable; any other value enables tracing AND
#: names the chrome-trace file exported when the reader joins.
TRACE_ENV_VAR = 'PETASTORM_TPU_TRACE'

#: Default ring-buffer bound: ~7 tuple slots per span keeps 100k spans in the
#: low tens of MB while covering minutes of steady-state pipeline activity.
DEFAULT_CAPACITY = 100_000

#: A recorded span: (name, cat, start_s, dur_s, pid, tid, args-or-None).
#: ``start_s`` is a ``time.perf_counter()`` reading; ``dur_s`` seconds.
Span = Tuple[str, str, float, float, int, int, Optional[dict]]

#: Category of the per-step goodput spans
#: (:class:`~petastorm_tpu.goodput.GoodputMonitor` records one complete
#: ``'step'`` span per training step, args carrying the verdict/stall ms).
GOODPUT_STEP_CAT = 'goodput'


def step_stall_marker(event: dict) -> Optional[dict]:
    """An instant step-boundary marker for a data-stalled goodput step.

    Given a chrome-trace ``'X'`` event, returns a process-scoped instant
    (``ph='i'``) event at the step boundary naming the stall — Perfetto
    renders these as flags, so stalled steps stand out on a busy pod
    timeline without opening each span's args. ``None`` for every other
    event. Used by both the single-host export and
    :func:`stitch_pod_trace`."""
    args = event.get('args') or {}
    if (event.get('cat') != GOODPUT_STEP_CAT
            or args.get('verdict') != 'data-stall'):
        return None
    return {'name': 'data-stall {}ms'.format(args.get('stall_ms')),
            'cat': GOODPUT_STEP_CAT, 'ph': 'i', 's': 'p',
            'ts': event['ts'], 'pid': event['pid'],
            'tid': event.get('tid', 0), 'args': dict(args)}


def resolve_trace(trace) -> Tuple[bool, Optional[str]]:
    """Resolve a factory's ``trace=`` kwarg against :data:`TRACE_ENV_VAR`.

    Returns ``(enabled, export_path)``. ``trace=None`` defers to the env var;
    ``trace=True``/``False`` force; a string value enables tracing and names
    the chrome-trace file auto-exported at ``Reader.join()``.
    """
    if trace is None:
        value = os.environ.get(TRACE_ENV_VAR, '').strip()
        if not value or value.lower() in ('0', 'false', 'off'):
            return False, None
        if value.lower() in ('1', 'true', 'on'):
            return True, None
        return True, value
    if isinstance(trace, str):
        return True, trace
    return bool(trace), None


def make_span(name: str, cat: str, start_s: float, dur_s: float,
              pid: Optional[int] = None, tid: Optional[int] = None,
              args: Optional[dict] = None) -> Span:
    """Build one span tuple, stamping the calling thread/process when the
    caller does not supply a track."""
    return (name, cat, start_s, dur_s,
            os.getpid() if pid is None else pid,
            threading.get_ident() if tid is None else tid,
            args)


class Tracer:
    """Thread-safe bounded ring buffer of pipeline spans.

    One instance lives on the worker pool (``pool.tracer``, reachable as
    ``reader.tracer`` / ``loader.tracer``) when tracing is enabled; thread
    and dummy pools record into it directly, process workers accumulate spans
    locally (``WorkerBase.record_span``) and the pool :meth:`merge`\\ s the
    batches shipped back in the accounting message.
    """

    __slots__ = ('_lock', '_spans', '_added', '_origin_pid')

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError('capacity must be >= 1, got {}'.format(capacity))
        self._lock = threading.Lock()
        self._spans: 'deque[Span]' = deque(maxlen=capacity)
        self._added = 0
        # the constructing process is the consumer: its pid names the
        # consumer track in the export metadata
        self._origin_pid = os.getpid()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def dropped(self) -> int:
        """Spans evicted by the ring bound since construction/reset."""
        with self._lock:
            return self._added - len(self._spans)

    def add_span(self, name: str, cat: str, start_s: float, dur_s: float,
                 pid: Optional[int] = None, tid: Optional[int] = None,
                 args: Optional[dict] = None) -> None:
        span = make_span(name, cat, start_s, dur_s, pid, tid, args)
        with self._lock:
            self._spans.append(span)
            self._added += 1

    @contextmanager
    def span(self, name: str, cat: str = '', args: Optional[dict] = None):
        """Record a complete span around the with-block."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add_span(name, cat, start, time.perf_counter() - start,
                          args=args)

    def merge(self, spans) -> None:
        """Append a batch of span tuples (shipped back from a worker)."""
        if not spans:
            return
        with self._lock:
            self._spans.extend(spans)
            self._added += len(spans)

    def reset(self) -> None:
        """Drop every recorded span (benchmarks call this after warmup so the
        exported window covers only the measured passes)."""
        with self._lock:
            self._spans.clear()
            self._added = 0

    def spans(self) -> List[Span]:
        """A point-in-time copy of the buffered spans."""
        with self._lock:
            return list(self._spans)

    # -- chrome trace-event export ---------------------------------------------

    def chrome_trace_events(self) -> List[dict]:
        """The buffered spans as Chrome trace-event dicts: complete events
        (``ph='X'``, ``ts``/``dur`` in microseconds) sorted by timestamp,
        preceded by ``process_name`` metadata naming the consumer vs worker
        tracks."""
        spans = self.spans()
        spans.sort(key=lambda s: s[2])
        events: List[dict] = []
        for pid in sorted({s[4] for s in spans}):
            role = 'consumer' if pid == self._origin_pid else 'worker'
            events.append({'name': 'process_name', 'ph': 'M', 'pid': pid,
                           'tid': 0,
                           'args': {'name': 'petastorm_tpu {} (pid {})'
                                    .format(role, pid)}})
        for name, cat, start_s, dur_s, pid, tid, args in spans:
            event = {'name': name, 'cat': cat or 'pipeline', 'ph': 'X',
                     'ts': start_s * 1e6, 'dur': max(0.0, dur_s) * 1e6,
                     'pid': pid, 'tid': tid}
            if args:
                event['args'] = args
            events.append(event)
            marker = step_stall_marker(event)
            if marker is not None:
                events.append(marker)
        return events

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffered spans as Chrome trace-event JSON (open the file
        in Perfetto / ``chrome://tracing``). Returns the number of span
        events written.

        The write is atomic (tmp file + ``os.replace``): a crash mid-dump —
        exactly when traces matter most — must never leave a truncated JSON
        that Perfetto rejects, and a previous good export at the same path
        survives a failed rewrite."""
        from petastorm_tpu.utils import atomic_write
        events = self.chrome_trace_events()
        atomic_write(path, lambda f: json.dump(
            {'traceEvents': events, 'displayTimeUnit': 'ms'}, f))
        return sum(1 for e in events if e['ph'] == 'X')

    def tail(self, limit: int = 500) -> List[dict]:
        """The most recent ``limit`` spans as JSON-able dicts (same field
        names as the chrome events: ``ts``/``dur`` in microseconds). The
        flight recorder embeds this ring tail in its stall dump — the last
        thing the pipeline did before it stopped doing anything."""
        if limit < 1:
            return []
        with self._lock:
            spans = list(self._spans)[-limit:]
        return [{'name': name, 'cat': cat or 'pipeline',
                 'ts': start_s * 1e6, 'dur': max(0.0, dur_s) * 1e6,
                 'pid': pid, 'tid': tid, 'args': args}
                for name, cat, start_s, dur_s, pid, tid, args in spans]


def stitch_pod_trace(tracks: List[dict], path: str) -> str:
    """Stitch per-host span tails into ONE clock-aligned chrome trace.

    ``tracks`` is the ``trace_tracks`` list of a
    :class:`~petastorm_tpu.podobs.PodObserver` report: one entry per host
    with ``host``, ``pid``, ``clock_offset_s`` (that host's monotonic clock
    minus the aggregator's, estimated from the poll round trip) and
    ``spans`` (tail dicts, ``ts``/``dur`` in µs on the HOST's clock). Every
    span's ``ts`` is shifted by ``-clock_offset_s`` onto the aggregator's
    timeline, and each distinct ``(host, pid)`` pair is remapped to a
    unique synthetic pid with a ``process_name`` metadata row naming the
    host — two hosts' identical OS pids must not collapse into one
    Perfetto track. The write is atomic (a crash never leaves a truncated
    JSON). Returns ``path``."""
    from petastorm_tpu.utils import atomic_write
    events: List[dict] = []
    pid_map: Dict[Tuple[str, object], int] = {}
    for track in tracks:
        host = str(track.get('host'))
        offset_s = track.get('clock_offset_s') or 0.0
        offset_us = float(offset_s) * 1e6
        for span in track.get('spans') or []:
            key = (host, span.get('pid'))
            pid = pid_map.get(key)
            if pid is None:
                pid = pid_map[key] = len(pid_map) + 1
                events.append({
                    'name': 'process_name', 'ph': 'M', 'pid': pid, 'tid': 0,
                    'args': {'name': 'petastorm_tpu {} (pid {})'.format(
                        host, span.get('pid'))}})
            event = {'name': span.get('name'),
                     'cat': span.get('cat') or 'pipeline', 'ph': 'X',
                     'ts': float(span.get('ts', 0.0)) - offset_us,
                     'dur': max(0.0, float(span.get('dur', 0.0))),
                     'pid': pid, 'tid': span.get('tid', 0)}
            if span.get('args'):
                event['args'] = span['args']
            events.append(event)
            marker = step_stall_marker(event)
            if marker is not None:
                # stalled step boundaries get a flag on the stitched pod
                # timeline, already shifted onto the aggregator's clock
                events.append(marker)
    events.sort(key=lambda e: (e['ph'] != 'M', e.get('ts', 0.0)))
    atomic_write(path, lambda f: json.dump(
        {'traceEvents': events, 'displayTimeUnit': 'ms'}, f))
    return path


def _prometheus_value(value: float) -> str:
    """One sample value per the text-exposition format: finite floats print
    normally, non-finite ones as the spec's ``NaN``/``+Inf``/``-Inf``
    literals (``float()`` would print ``nan``/``inf``, which scrape parsers
    reject — derived ratios can legitimately be non-finite)."""
    value = float(value)
    if math.isnan(value):
        return 'NaN'
    if math.isinf(value):
        return '+Inf' if value > 0 else '-Inf'
    return repr(value)


def prometheus_text(snapshot: dict, prefix: str = 'petastorm_tpu') -> str:
    """A stats snapshot in Prometheus text-exposition format — the one
    formatter shared by :class:`MetricsEmitter` (``.prom`` textfile
    collector output) and the debug endpoint's ``/metrics`` route.
    Non-numeric values are skipped; everything is exposed as a gauge (the
    snapshot is a point-in-time scrape, not a counter stream) with a
    ``# HELP`` line, and non-finite values use the spec's
    ``NaN``/``+Inf``/``-Inf`` literals.

    Two string keys are special-cased as info-style labeled gauges (the
    Prometheus idiom for categorical state): ``binding_stage`` (the
    roofline profiler's verdict — see ``docs/profiling.md``) exports as
    ``<prefix>_binding_stage{stage="decode"} 1``, and
    ``autotune_last_knob`` (the controller's most recent move — see
    ``docs/autotune.md``) as
    ``<prefix>_autotune_last_knob{knob="workers_count:up"} 1``.

    When the snapshot carries the latency plane's histogram states (the
    ``'_latency_histograms'`` key a ``ReaderStats`` snapshot includes unless
    kill-switched — see ``docs/latency.md``), each stage renders in the
    spec's **histogram** form: cumulative ``<prefix>_latency_<stage>_seconds_bucket``
    samples with ``le`` labels, the mandatory terminal ``le="+Inf"`` bucket,
    and ``_sum``/``_count`` — scrapeable by any Prometheus-conformant
    parser, quantile-queryable via ``histogram_quantile()``."""
    from petastorm_tpu.latency import prometheus_histogram_lines
    from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY
    lines = []
    histograms = snapshot.get(LATENCY_HISTOGRAMS_KEY)
    if isinstance(histograms, dict):
        for stage in sorted(histograms):
            metric = '{}_latency_{}_seconds'.format(prefix, stage)
            lines.extend(prometheus_histogram_lines(
                metric, histograms[stage],
                help_text='petastorm_tpu {} duration distribution '
                          '(see docs/latency.md)'.format(stage)))
    for key in sorted(snapshot):
        if key == LATENCY_HISTOGRAMS_KEY:
            continue
        value = snapshot[key]
        if key == 'binding_stage' and isinstance(value, str) and value:
            metric = '{}_{}'.format(prefix, key)
            lines.append('# HELP {} the roofline profiler\'s binding '
                         'pipeline stage (see docs/profiling.md)'
                         .format(metric))
            lines.append('# TYPE {} gauge'.format(metric))
            lines.append('{}{{stage="{}"}} 1'.format(metric, value))
            continue
        if key == 'autotune_last_knob' and isinstance(value, str) and value:
            metric = '{}_{}'.format(prefix, key)
            lines.append('# HELP {} the autotune controller\'s most recent '
                         'knob move (see docs/autotune.md)'.format(metric))
            lines.append('# TYPE {} gauge'.format(metric))
            lines.append('{}{{knob="{}"}} 1'.format(metric, value))
            continue
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        metric = '{}_{}'.format(prefix, key)
        lines.append('# HELP {} petastorm_tpu reader stat {!r} '
                     '(see docs/transport.md key table)'.format(metric, key))
        lines.append('# TYPE {} gauge'.format(metric))
        lines.append('{} {}'.format(metric, _prometheus_value(value)))
    return '\n'.join(lines) + '\n'


class MetricsEmitter:
    """Background thread snapshotting a stats source every ``interval_s``
    seconds to a file.

    Formats (picked from the path suffix unless ``fmt`` is given):

    - ``jsonl`` — one JSON object per snapshot appended per line, with
      ``ts`` (epoch seconds) added; tail it or ship it to a log pipeline.
    - ``prometheus`` (``.prom`` suffix) — Prometheus text-exposition format,
      atomically rewritten each snapshot; point a node-exporter textfile
      collector at it.

    A final snapshot is emitted at :meth:`stop` so short runs always record
    at least one sample. ``Reader.stop()/join()`` drive the lifecycle.
    """

    def __init__(self, snapshot_fn: Callable[[], dict], interval_s: float,
                 path: str, fmt: Optional[str] = None,
                 prefix: str = 'petastorm_tpu'):
        if interval_s <= 0:
            raise ValueError('interval_s must be positive, got '
                             '{!r}'.format(interval_s))
        if fmt is None:
            fmt = 'prometheus' if str(path).endswith('.prom') else 'jsonl'
        if fmt not in ('jsonl', 'prometheus'):
            raise ValueError("fmt must be 'jsonl' or 'prometheus', got "
                             '{!r}'.format(fmt))
        self._snapshot_fn = snapshot_fn
        self._interval = interval_s
        self._path = str(path)
        self._fmt = fmt
        self._prefix = prefix
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._emit_lock = threading.Lock()
        self._final_emitted = False
        self.emit_count = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='petastorm-tpu-metrics')
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_event.wait(self._interval):
            self.emit_once()

    def emit_once(self) -> None:
        snapshot = dict(self._snapshot_fn())
        if self._fmt == 'jsonl':
            # jsonl lines stay scalar: the raw histogram states (137-bucket
            # count pairs per stage per tick) belong to the .prom/scrape
            # path; the derived *_p50_s/*_p99_s keys carry the tail signal
            from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY
            snapshot.pop(LATENCY_HISTOGRAMS_KEY, None)
            # deliberate wall clock: 'ts' is a log-pipeline timestamp for
            # humans and scrapers, never compared against monotonic readings
            ts = time.time()  # petalint: disable=monotonic-clock
            line = json.dumps({'ts': ts, **snapshot}, sort_keys=True)
        # _emit_lock exists precisely to serialize emissions (periodic tick
        # vs the final flush at stop()); holding it across the write IS the
        # point, and only those two threads ever contend on it
        with self._emit_lock:
            if self._fmt == 'jsonl':
                with open(self._path, 'a') as f:  # petalint: disable=lock-discipline
                    f.write(line + '\n')
            else:
                self._write_prometheus(snapshot)
            self.emit_count += 1

    def _write_prometheus(self, snapshot: dict) -> None:
        from petastorm_tpu.utils import atomic_write
        atomic_write(self._path,
                     lambda f: f.write(prometheus_text(snapshot,
                                                       self._prefix)))

    def stop(self, join: bool = True) -> None:
        """Signal the thread to stop; with ``join`` (the default) also wait
        for it and emit one final snapshot. Idempotent."""
        self._stop_event.set()
        if not join:
            return
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None
        if not self._final_emitted:
            self._final_emitted = True
            self.emit_once()
