"""Reader core: ``make_reader`` / ``make_batch_reader`` factories and the
``Reader`` orchestrator.

Reference parity: ``petastorm/reader.py`` — ``make_reader`` (:61-195),
``make_batch_reader`` (:198-327), ``Reader`` (:330-676): constructor pipeline
(:384-462), row-group filtering by predicate/selector/shard (:498-608),
ventilation (:622-637), iterator protocol (:655-665), ``reset`` (:468-492),
context manager (:670-676), diagnostics (:648-650).

TPU-first deviations:
 - ``seed`` gives a reproducible epoch shuffle (ventilator is seeded).
 - ``cur_shard``/``shard_count`` default to the JAX process if
   ``shard_by_jax_process=True`` is passed (multi-host pods read disjoint
   row-group shards; see SURVEY.md §2 "Parallelism accounting").
 - The reader never touches the TPU: it produces numpy/namedtuple rows.
   Device staging lives in :mod:`petastorm_tpu.jax_utils`.
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

from petastorm_tpu.cache import LocalDiskCache, NullCache
from petastorm_tpu.codecs import build_decode_overrides
from petastorm_tpu.errors import NoDataAvailableError, PetastormMetadataError
from petastorm_tpu.etl.dataset_metadata import (get_schema, infer_or_load_unischema,
                                                load_row_groups)
from petastorm_tpu.filters import (FiltersPredicate, RowGroupStatsEvaluator,
                                   filter_column_names, normalize_filters,
                                   validate_filter_types)
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dataset_url_or_urls
from petastorm_tpu.health import (DEFAULT_STALL_AFTER_S, DebugServer,
                                  HealthMonitor, PipelineWatchdog,
                                  build_flight_record, resolve_debug_port,
                                  write_flight_record)
from petastorm_tpu.lineage import (BatchProvenance, CoverageAuditor,
                                   LineageTracker, batch_provenance_of,
                                   lineage_enabled, unwrap_envelope,
                                   validate_decode_error_policy)
from petastorm_tpu.lineage import replay as _lineage_replay
from petastorm_tpu.ngram import NGram
from petastorm_tpu.predicates import in_reduce
from petastorm_tpu.readers.batch_worker import ArrowBatchWorker, BatchResultsReader
from petastorm_tpu.readers.columnar_worker import ColumnarResultsReader, ColumnarWorker
from petastorm_tpu.readers.row_worker import RowGroupResultsReader, RowGroupWorker
from petastorm_tpu.tracing import MetricsEmitter, Tracer, resolve_trace
from petastorm_tpu.transform import transform_schema
from petastorm_tpu.unischema import match_unischema_fields
from petastorm_tpu.utils import cast_partition_value
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.process_pool import ProcessPool
from petastorm_tpu.workers.serializers import ArrowTableSerializer, ZeroCopySerializer
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator

logger = logging.getLogger(__name__)

#: Extra row groups ventilated beyond the worker count, keeping workers busy
#: without unbounded decode-ahead (reference ``reader.py:46``).
_VENTILATE_EXTRA_ROWGROUPS = 2


def _validate_io_readahead(io_readahead):
    """Normalize the ``io_readahead`` knob: 0/None disables, a positive int is
    a fixed per-worker prefetch depth, ``'auto'`` sizes it live from the
    worker's measured io:decode ratio."""
    if io_readahead in (None, 0):
        return 0
    if io_readahead == 'auto':
        return 'auto'
    if isinstance(io_readahead, int) and io_readahead > 0:
        return io_readahead
    raise ValueError("io_readahead must be a non-negative int or 'auto', got "
                     '{!r}'.format(io_readahead))


#: Valid ``cache_type`` values for every reader factory (see
#: ``docs/cache.md``): no caching, a per-reader pickle-on-disk cache, or the
#: host-wide tiered shared decoded cache.
CACHE_TYPES = ('null', 'local-disk', 'shared')


def _make_cache(cache_type, cache_location, cache_size_limit, cache_row_size_estimate,
                cache_extra_settings):
    if cache_type in (None, 'null'):
        return NullCache()
    if cache_type == 'local-disk':
        if not cache_location or not cache_size_limit:
            raise ValueError("cache_type='local-disk' needs cache_location and "
                             'cache_size_limit')
        return LocalDiskCache(cache_location, cache_size_limit,
                              cache_row_size_estimate or 0,
                              **(cache_extra_settings or {}))
    if cache_type == 'shared':
        if not cache_location or not cache_size_limit:
            raise ValueError("cache_type='shared' needs cache_location and "
                             'cache_size_limit')
        from petastorm_tpu.sharedcache import (SharedRowGroupCache,
                                               shared_cache_enabled)
        if not shared_cache_enabled():
            # kill switch: no attachment, no files, no shared state at all
            logger.warning(
                "cache_type='shared' disabled via %s=0; reads are uncached",
                'PETASTORM_TPU_SHARED_CACHE')
            return NullCache()
        return SharedRowGroupCache(cache_location, cache_size_limit,
                                   **(cache_extra_settings or {}))
    raise ValueError('cache_type must be one of {}; got {!r}'.format(
        ', '.join(repr(t) for t in CACHE_TYPES), cache_type))


def _make_pool(reader_pool_type, workers_count, results_queue_size, serializer,
               zmq_copy_buffers, profiling_enabled=False, tracer=None,
               recovery=None):
    if reader_pool_type == 'thread':
        return ThreadPool(workers_count, results_queue_size,
                          profiling_enabled=profiling_enabled, tracer=tracer,
                          recovery=recovery)
    if reader_pool_type == 'process':
        return ProcessPool(workers_count, serializer=serializer,
                           zmq_copy_buffers=zmq_copy_buffers, tracer=tracer,
                           recovery=recovery)
    if reader_pool_type == 'dummy':
        return DummyPool(tracer=tracer)
    raise ValueError("reader_pool_type must be one of 'thread', 'process', 'dummy'; "
                     'got {!r}'.format(reader_pool_type))


def _make_tracer(trace):
    """Resolve the ``trace=`` kwarg (and :data:`~petastorm_tpu.tracing.TRACE_ENV_VAR`)
    into ``(Tracer-or-None, export_path-or-None)``."""
    enabled, export_path = resolve_trace(trace)
    return (Tracer() if enabled else None), export_path


def _relax_hinted_shapes(schema, decode_hints, stored_schema):
    """Copy of ``schema`` with the spatial dims of hinted fields made dynamic
    (``None`` wildcards) — scaled decode changes them at read time. A field
    whose shape a TransformSpec redeclared (differs from the stored shape,
    e.g. a resize to a fixed size) keeps its declared shape."""
    from petastorm_tpu.unischema import Unischema, UnischemaField
    fields = []
    for f in schema.fields.values():
        stored = stored_schema.fields.get(f.name)
        # only fields the codec can actually scale get dynamic dims — a
        # hinted field decode_scaled always passes through (png, uint16,
        # RGBA) keeps its exact static shape
        scalable = (stored is not None
                    and getattr(stored.codec, 'can_scale',
                                lambda _f: False)(stored))
        if (f.name in decode_hints and scalable and f.shape
                and len(f.shape) >= 2 and f.shape == stored.shape):
            f = UnischemaField(f.name, f.numpy_dtype,
                               (None, None) + tuple(f.shape[2:]),
                               f.codec, f.nullable)
        fields.append(f)
    return Unischema(schema._name, fields)


def _validate_shard_range(cur_shard, shard_count):
    """Fail at the factory with a message naming both values — a bad shard
    spec must not surface as an empty iterator or a ventilator IndexError
    deep inside the pipeline."""
    if cur_shard is None and shard_count is None:
        return
    if (cur_shard is None) != (shard_count is None):
        raise ValueError('cur_shard and shard_count must be specified together '
                         '(got cur_shard={!r}, shard_count={!r})'.format(
                             cur_shard, shard_count))
    if shard_count < 1:
        raise ValueError('shard_count must be a positive integer, got '
                         'shard_count={!r} (with cur_shard={!r})'.format(
                             shard_count, cur_shard))
    if cur_shard < 0:
        raise ValueError('cur_shard must be non-negative, got cur_shard={!r} '
                         '(with shard_count={!r})'.format(
                             cur_shard, shard_count))
    if cur_shard >= shard_count:
        raise ValueError('cur_shard must be < shard_count, got cur_shard={!r} '
                         'for shard_count={!r}'.format(cur_shard, shard_count))


def _resolve_jax_shard(cur_shard, shard_count, shard_by_jax_process,
                       elastic=None):
    if elastic is not None:
        # lease-driven shard assignment: the elasticity plane derives
        # (cur_shard, shard_count) from the live pod membership (import is
        # local so the default-off plane costs nothing when unused)
        from petastorm_tpu.podelastic import resolve_elastic_shard
        cur_shard, shard_count, _ = resolve_elastic_shard(
            elastic, cur_shard, shard_count, shard_by_jax_process)
        _validate_shard_range(cur_shard, shard_count)
        return cur_shard, shard_count
    if not shard_by_jax_process:
        _validate_shard_range(cur_shard, shard_count)
        return cur_shard, shard_count
    if cur_shard is not None or shard_count is not None:
        raise ValueError('shard_by_jax_process is mutually exclusive with explicit '
                         'cur_shard/shard_count')
    import jax
    cur_shard, shard_count = jax.process_index(), jax.process_count()
    _validate_shard_range(cur_shard, shard_count)
    return cur_shard, shard_count


def make_reader(dataset_url,
                schema_fields=None,
                reader_pool_type='thread', workers_count=10, results_queue_size=50,
                seed=None, shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                predicate=None, rowgroup_selector=None,
                num_epochs=1,
                cur_shard=None, shard_count=None, shard_by_jax_process=False,
                cache_type='null', cache_location=None, cache_size_limit=None,
                cache_row_size_estimate=None, cache_extra_settings=None,
                transform_spec=None, filters=None,
                storage_options=None, zmq_copy_buffers=True,
                profiling_enabled=False, decode_hints=None,
                io_readahead=0, trace=None, metrics_interval=0,
                metrics_out=None, debug_port=None, stall_timeout=0,
                flight_record_dir=None, on_decode_error='raise',
                slo=None, autotune=False, retry=None, hedge=None,
                remote_read=None, worker_recovery=None, elastic=None):
    """Row-granular reader for petastorm_tpu datasets (codec-decoded rows).

    Mirrors the reference factory (``reader.py:61-195``). Raises a helpful error
    directing to :func:`make_batch_reader` when the store lacks petastorm
    metadata (reference behavior at ``reader.py:128-141``).

    With ``reader_pool_type='process'`` payloads cross the worker boundary
    over the zero-copy transport: large (≥64 KB) contiguous arrays arrive as
    **read-only** views over the transport frames (see ``docs/transport.md``).
    Consumers that mutate samples in place must copy first; batching
    (``JaxDataLoader`` collation, shuffling buffers) already copies.

    ``io_readahead=K`` gives each worker a background reader that prefetches
    the parquet reads of its next K ventilated pieces while it decodes the
    current one, overlapping storage latency with decode CPU; ``'auto'``
    sizes K from the live io:decode ratio (see ``docs/readahead.md``).

    ``cache_type`` picks the row-group cache: ``'null'`` (none, the
    default), ``'local-disk'`` (per-reader pickle-on-disk), or ``'shared'``
    — the host-wide tiered cache (shared-memory decoded segments, disk
    spill) that N concurrent readers and their worker processes attach to
    so each row group is read+decoded ONCE per host; a shared-tier miss
    still prefetches via the readahead planner with coalesced remote reads.
    Shared-cache hits return **read-only** zero-copy views. Kill switch:
    ``PETASTORM_TPU_SHARED_CACHE=0``. See ``docs/cache.md``.

    ``trace=True`` (or the ``PETASTORM_TPU_TRACE`` env var) records per-item
    spans for every pipeline stage into ``reader.tracer``, exportable as
    Chrome trace-event JSON for Perfetto; ``metrics_interval=N`` starts a
    background emitter snapshotting the reader's stats every N seconds into
    ``metrics_out`` (JSON-lines, or Prometheus text for ``.prom`` paths).
    See ``docs/tracing.md``.

    ``debug_port=N`` (or ``PETASTORM_TPU_DEBUG_PORT``) serves the live
    health endpoints on ``127.0.0.1:N`` (``/healthz`` ``/metrics``
    ``/diagnostics`` ``/stacks``; ``0`` = ephemeral, read
    ``reader.debug_port``); ``stall_timeout=S`` arms a background watchdog
    that classifies the pipeline from per-entity heartbeats and writes a
    flight-recorder JSON into ``flight_record_dir`` when no entity has made
    progress for S seconds. See ``docs/health.md``.

    Every yielded item carries sample lineage by default (``reader.lineage``
    ledgers, ``reader.explain_batch()``, ``reader.replay()``, the
    ``/coverage`` debug route; kill switch ``PETASTORM_TPU_LINEAGE=0``).
    ``on_decode_error`` picks the bad-sample policy: ``'raise'`` (default)
    propagates decode/transform exceptions, ``'skip'`` drops the failing
    rows counting them, ``'quarantine'`` drops them AND records
    provenance-tagged quarantine records. See ``docs/lineage.md``.

    ``autotune=True`` (or an options dict; job-wide via
    ``PETASTORM_TPU_AUTOTUNE=1``, kill switch ``=0``) starts the
    model-predictive pipeline controller: a background thread that
    live-resizes the worker pool, readahead depth, ventilation window and
    results-queue bound toward the roofline model's best predicted
    configuration, with hysteresis, per-knob cooldowns and
    revert-on-regression. Every action is observable via ``/autotune``,
    flight records and ``/metrics``. See ``docs/autotune.md``.

    Fault tolerance (``docs/robustness.md``): ``retry=`` (default ON)
    retries transient storage errors under the shared
    :class:`~petastorm_tpu.resilience.RetryPolicy` (full-jitter backoff,
    total-wall cap; permanent errors fail in one attempt); ``hedge=``
    (default off; ``True``, a threshold in seconds, or an options dict)
    fires a duplicate row-group read when the first exceeds the live p95 —
    first result wins; ``worker_recovery=`` (default ON) respawns a crashed
    worker and re-ventilates its in-flight items exactly once, with bounded
    respawns and poison-item quarantine. ``PETASTORM_TPU_CHAOS`` arms the
    deterministic fault-injection harness.

    ``remote_read=`` picks the storage read plane
    (``docs/object_store.md``): ``'serial'`` (plain reads), ``'prebuffer'``
    (pyarrow-coalesced column chunks), ``'ranged'`` (explicit footer-planned
    parallel range fetches; retry/hedge then apply per RANGE, not per row
    group). Default auto: ``prebuffer`` for object stores, ``serial`` local.

    ``elastic=`` (a ``{'coord_root': ...}`` dict; default off, kill switch
    ``PETASTORM_TPU_ELASTIC=0``) derives ``(cur_shard, shard_count)`` from
    the live pod membership instead of static arguments — a **snapshot**
    taken at construction; mid-epoch host death/join rebalancing lives in
    the lease-grid plane (``petastorm_tpu.podelastic``,
    ``docs/robustness.md``). Mutually exclusive with explicit
    ``cur_shard``/``shard_count`` and ``shard_by_jax_process``.
    """
    dataset_url = normalize_dataset_url_or_urls(dataset_url)
    fs, path, factory = get_filesystem_and_path_or_paths(dataset_url, storage_options)
    if isinstance(path, list):
        raise ValueError('make_reader supports a single dataset url; a list of file '
                         'urls is only supported by make_batch_reader')
    try:
        get_schema(fs, path)
    except PetastormMetadataError as e:
        raise RuntimeError(
            'Dataset at {} is missing petastorm_tpu metadata ({}). If this is a plain '
            'parquet store, use make_batch_reader instead.'.format(dataset_url, e))

    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings)
    tracer, trace_export = _make_tracer(trace)
    from petastorm_tpu.resilience import resolve_recovery
    # ZeroCopySerializer: decoded ndarray payloads cross the process boundary
    # as out-of-band ZMQ frames instead of being memcpy'd into a pickle blob
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      ZeroCopySerializer(), zmq_copy_buffers, profiling_enabled,
                      tracer=tracer, recovery=resolve_recovery(worker_recovery))
    cur_shard, shard_count = _resolve_jax_shard(cur_shard, shard_count,
                                                 shard_by_jax_process, elastic)
    return Reader(factory, path,
                  worker_class=RowGroupWorker,
                  results_reader_factory=RowGroupResultsReader,
                  schema_fields=schema_fields, seed=seed,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  cache=cache, transform_spec=transform_spec, filters=filters,
                  pool=pool, is_batched_reader=False, decode_hints=decode_hints,
                  io_readahead=io_readahead, trace_export=trace_export,
                  metrics_interval=metrics_interval, metrics_out=metrics_out,
                  debug_port=debug_port, stall_timeout=stall_timeout,
                  flight_record_dir=flight_record_dir,
                  on_decode_error=on_decode_error, slo=slo,
                  autotune=autotune, retry=retry, hedge=hedge,
                  remote_read=remote_read)


def make_columnar_reader(dataset_url,
                         schema_fields=None,
                         reader_pool_type='thread', workers_count=10,
                         results_queue_size=50,
                         seed=None, shuffle_row_groups=True,
                         shuffle_row_drop_partitions=1,
                         predicate=None, rowgroup_selector=None,
                         num_epochs=1,
                         cur_shard=None, shard_count=None, shard_by_jax_process=False,
                         cache_type='null', cache_location=None, cache_size_limit=None,
                         cache_row_size_estimate=None, cache_extra_settings=None,
                         transform_spec=None, filters=None,
                         storage_options=None, zmq_copy_buffers=True,
                         profiling_enabled=False, decode_hints=None,
                         io_readahead=0, trace=None, metrics_interval=0,
                         metrics_out=None, debug_port=None, stall_timeout=0,
                         flight_record_dir=None, on_decode_error='raise',
                         slo=None, autotune=False, retry=None, hedge=None,
                         remote_read=None, worker_recovery=None,
                         elastic=None):
    """Vectorized codec-decoded reader for petastorm_tpu datasets.

    Yields **batch namedtuples of decoded numpy column arrays** (one per row
    group), with no per-row Python work anywhere on the path — the layout the
    JAX adapter wants. This is the high-throughput way to read codec datasets;
    ``make_reader`` remains the row-granular analogue of the reference API.

    Differences from :func:`make_reader`: ``batched_output=True``; NGram is not
    supported (windows are row-granular); ``TransformSpec.func`` receives a
    dict of column arrays instead of a row dict.

    With ``reader_pool_type='process'`` the published column arrays arrive
    over the zero-copy transport as **read-only** views over the transport
    frames (see ``docs/transport.md``); copy before mutating in place.
    """
    dataset_url = normalize_dataset_url_or_urls(dataset_url)
    fs, path, factory = get_filesystem_and_path_or_paths(dataset_url, storage_options)
    if isinstance(path, list):
        raise ValueError('make_columnar_reader supports a single dataset url; a list '
                         'of file urls is only supported by make_batch_reader')
    if isinstance(schema_fields, NGram):
        raise ValueError('NGram is not supported by make_columnar_reader; use '
                         'make_reader for windowed sequence assembly')
    try:
        get_schema(fs, path)
    except PetastormMetadataError as e:
        raise RuntimeError(
            'Dataset at {} is missing petastorm_tpu metadata ({}). If this is a plain '
            'parquet store, use make_batch_reader instead.'.format(dataset_url, e))

    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings)
    tracer, trace_export = _make_tracer(trace)
    from petastorm_tpu.resilience import resolve_recovery
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      ZeroCopySerializer(), zmq_copy_buffers, profiling_enabled,
                      tracer=tracer, recovery=resolve_recovery(worker_recovery))
    cur_shard, shard_count = _resolve_jax_shard(cur_shard, shard_count,
                                                 shard_by_jax_process, elastic)
    return Reader(factory, path,
                  worker_class=ColumnarWorker,
                  results_reader_factory=ColumnarResultsReader,
                  schema_fields=schema_fields, seed=seed,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  cache=cache, transform_spec=transform_spec, filters=filters,
                  pool=pool, is_batched_reader=True, decode_hints=decode_hints,
                  io_readahead=io_readahead, trace_export=trace_export,
                  metrics_interval=metrics_interval, metrics_out=metrics_out,
                  debug_port=debug_port, stall_timeout=stall_timeout,
                  flight_record_dir=flight_record_dir,
                  on_decode_error=on_decode_error, slo=slo,
                  autotune=autotune, retry=retry, hedge=hedge,
                  remote_read=remote_read)


def make_batch_reader(dataset_url_or_urls,
                      schema_fields=None,
                      reader_pool_type='thread', workers_count=10, results_queue_size=50,
                      seed=None, shuffle_row_groups=True,
                      predicate=None,
                      num_epochs=1,
                      cur_shard=None, shard_count=None, shard_by_jax_process=False,
                      cache_type='null', cache_location=None, cache_size_limit=None,
                      cache_row_size_estimate=None, cache_extra_settings=None,
                      transform_spec=None, filters=None,
                      storage_options=None, zmq_copy_buffers=True,
                      profiling_enabled=False, io_readahead=0, trace=None,
                      metrics_interval=0, metrics_out=None, debug_port=None,
                      stall_timeout=0, flight_record_dir=None,
                      on_decode_error='raise', slo=None, autotune=False,
                      retry=None, hedge=None, remote_read=None,
                      worker_recovery=None, elastic=None):
    """Vectorized batch reader for arbitrary parquet stores
    (reference ``reader.py:198-327``). Yields namedtuples of column arrays,
    one per row group. ``io_readahead`` prefetches upcoming row-group reads
    per worker; ``trace``/``metrics_interval``/``metrics_out`` enable the
    span tracer and metrics emitter; ``debug_port``/``stall_timeout``/
    ``flight_record_dir`` the live health layer (see :func:`make_reader`)."""
    dataset_url_or_urls = normalize_dataset_url_or_urls(dataset_url_or_urls)
    fs, path, factory = get_filesystem_and_path_or_paths(dataset_url_or_urls,
                                                         storage_options)
    if schema_fields is not None and not (
            isinstance(schema_fields, list)
            and all(isinstance(f, str) for f in schema_fields)):
        raise ValueError('make_batch_reader schema_fields must be a list of regex '
                         'strings (UnischemaField selection and NGram are row-reader '
                         'features)')
    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings)
    tracer, trace_export = _make_tracer(trace)
    from petastorm_tpu.resilience import resolve_recovery
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      ArrowTableSerializer(), zmq_copy_buffers, profiling_enabled,
                      tracer=tracer, recovery=resolve_recovery(worker_recovery))
    cur_shard, shard_count = _resolve_jax_shard(cur_shard, shard_count,
                                                 shard_by_jax_process, elastic)
    return Reader(factory, path,
                  worker_class=ArrowBatchWorker,
                  results_reader_factory=BatchResultsReader,
                  schema_fields=schema_fields, seed=seed,
                  shuffle_row_groups=shuffle_row_groups, shuffle_row_drop_partitions=1,
                  predicate=predicate, rowgroup_selector=None,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  cache=cache, transform_spec=transform_spec, filters=filters,
                  pool=pool, is_batched_reader=True, io_readahead=io_readahead,
                  trace_export=trace_export, metrics_interval=metrics_interval,
                  metrics_out=metrics_out, debug_port=debug_port,
                  stall_timeout=stall_timeout,
                  flight_record_dir=flight_record_dir,
                  on_decode_error=on_decode_error, slo=slo,
                  autotune=autotune, retry=retry, hedge=hedge,
                  remote_read=remote_read)


class Reader:
    """Iterates rows (or batches) of a parquet dataset through a worker pool."""

    def __init__(self, filesystem_factory, dataset_path,
                 worker_class, results_reader_factory,
                 schema_fields=None, seed=None, shuffle_row_groups=True,
                 shuffle_row_drop_partitions=1, predicate=None, rowgroup_selector=None,
                 num_epochs=1, cur_shard=None, shard_count=None,
                 cache=None, transform_spec=None, filters=None,
                 pool=None, is_batched_reader=False, decode_hints=None,
                 io_readahead=0, trace_export=None, metrics_interval=0,
                 metrics_out=None, debug_port=None, stall_timeout=0,
                 flight_record_dir=None, on_decode_error='raise',
                 slo=None, autotune=False, retry=None, hedge=None,
                 remote_read=None):
        if (cur_shard is None) != (shard_count is None):
            raise ValueError('cur_shard and shard_count must be specified together')
        if cur_shard is not None and not 0 <= cur_shard < shard_count:
            raise ValueError('cur_shard {} out of range for shard_count {}'.format(
                cur_shard, shard_count))
        if predicate is not None and not isinstance(cache, NullCache):
            raise RuntimeError('Local cache is not supported together with predicates '
                               '(cached row groups would bypass predicate evaluation)')
        if metrics_interval and not metrics_out:
            raise ValueError('metrics_interval needs a metrics_out path to '
                             'emit snapshots into')
        if stall_timeout and stall_timeout < 0:
            raise ValueError('stall_timeout must be >= 0, got '
                             '{!r}'.format(stall_timeout))
        validate_decode_error_policy(on_decode_error)
        # resolve + validate the resilience knobs here (fail fast on a
        # typo'd option); workers re-resolve the stored shapes after
        # unpickling (docs/robustness.md)
        from petastorm_tpu.resilience import resolve_hedge, resolve_retry
        retry_options = resolve_retry(retry)
        hedge_options = resolve_hedge(hedge)
        # remote read plane (docs/object_store.md): validate here so a
        # typo'd mode fails the factory; None = per-protocol auto in the
        # worker ('prebuffer' remote / 'serial' local, the pre-knob shape)
        from petastorm_tpu.objectstore import resolve_remote_read
        remote_read = resolve_remote_read(remote_read)
        if slo:
            # fail fast on a typo'd target name; the monitor itself is
            # built after the pool (it reads the stats snapshot + latency)
            from petastorm_tpu.latency import validate_slo_targets
            slo = validate_slo_targets(slo)
        # resolve autotune BEFORE any pipeline state exists: a typo'd option
        # must fail the factory, and the PETASTORM_TPU_AUTOTUNE=0 kill
        # switch must yield a reader with no controller thread and no files
        from petastorm_tpu.autotune import resolve_autotune
        autotune_options = resolve_autotune(autotune)
        #: The reader's :class:`~petastorm_tpu.autotune.PipelineController`
        #: (``None`` unless autotune resolved on): serves ``/autotune`` and
        #: owns the live worker/readahead/window/queue knobs.
        self._controller = None
        #: The reader's :class:`~petastorm_tpu.latency.SLOMonitor`
        #: (``None`` unless built with ``slo=dict(...)``); serves ``/slo``
        #: and feeds the burn accounting from the watchdog tick.
        self._slo = None
        self._filesystem_factory = filesystem_factory
        self._dataset_path = dataset_path
        self._pool = pool
        self._is_batched_reader = is_batched_reader
        self._num_epochs = num_epochs
        self._trace_export = trace_export
        self._metrics_emitter = None
        self._watchdog = None
        self._debug_server = None
        #: The loader-attached :class:`~petastorm_tpu.goodput.GoodputMonitor`
        #: (``None`` until a JAX loader registers one, and always ``None``
        #: under ``PETASTORM_TPU_GOODPUT=0``); serves ``/goodput`` and the
        #: flight-record goodput section.
        self._goodput = None
        self._flight_record_dir = flight_record_dir
        self.last_row_consumed = False
        # -- roofline profiler state (see docs/profiling.md) ------------------
        #: Most recent :meth:`profile` result (``None`` until the first call).
        self._last_profile = None
        #: ``stage_ceiling_*`` / ``roofline_fraction`` / ``binding_stage``
        #: gauges merged into :meth:`_stats_snapshot` once a profile exists,
        #: so ``/metrics`` and the metrics emitter expose %-of-ceiling.
        self._roofline_gauges = {}
        self._pool_type = {'ProcessPool': 'process', 'ThreadPool': 'thread',
                           'DummyPool': 'dummy'}.get(type(pool).__name__,
                                                     'thread')
        self._cache_type = {'NullCache': 'null',
                            'LocalDiskCache': 'local-disk',
                            'SharedRowGroupCache': 'shared'}.get(
                                type(cache).__name__, 'null')
        #: The pipeline's :class:`~petastorm_tpu.health.HealthMonitor`:
        #: per-entity heartbeats from the ventilator, the pool's workers
        #: (plus their readahead threads), and — when wired via
        #: ``prefetch_to_device(..., health=...)`` — the loader's prefetch
        #: thread. ``reader.health.heartbeats()`` is the live record set.
        self.health = HealthMonitor()

        filesystem = filesystem_factory()
        stored_schema, _ = infer_or_load_unischema(filesystem, dataset_path)

        # -- schema view / ngram resolution (reference reader.py:408-441) ------
        self.ngram = schema_fields if isinstance(schema_fields, NGram) else None
        if self.ngram is not None:
            if is_batched_reader:
                raise ValueError('NGram is not supported by make_batch_reader')
            if not self.ngram.timestamp_overlap and shuffle_row_drop_partitions > 1:
                raise NotImplementedError(
                    'shuffle_row_drop_partitions is not supported with '
                    'timestamp_overlap=False (reference reader.py:420-422)')
            self.ngram.resolve_regex_field_names(stored_schema)
            ngram_field_names = self.ngram.get_all_field_names()
            view_fields = [stored_schema.fields[n] for n in ngram_field_names
                           if n in stored_schema.fields]
            view_schema = stored_schema.create_schema_view(view_fields)
        elif schema_fields is not None:
            if isinstance(schema_fields, list) and all(isinstance(f, str)
                                                       for f in schema_fields):
                matched = match_unischema_fields(stored_schema, schema_fields)
                if not matched:
                    raise ValueError('schema_fields {} matched no fields'.format(
                        schema_fields))
                view_schema = stored_schema.create_schema_view(matched)
            else:
                view_schema = stored_schema.create_schema_view(schema_fields)
        else:
            view_schema = stored_schema

        transformed_schema = (transform_schema(view_schema, transform_spec)
                              if transform_spec is not None else view_schema)
        if decode_hints:
            # hinted fields decode at reduced resolution: the consumer-facing
            # schema must advertise dynamic spatial dims, or adapters (TF
            # static shapes, columnar assembly asserts) would promise the
            # full-resolution shape the data no longer has. Workers keep the
            # original schema — decode_scaled needs the stored shape to pick
            # its denominator.
            transformed_schema = _relax_hinted_shapes(transformed_schema,
                                                      decode_hints,
                                                      stored_schema)
        #: The schema of the rows/batches this reader yields.
        self.schema = transformed_schema

        # -- row-group discovery + filtering (reference reader.py:498-608) -----
        footer_cache = {}
        all_pieces = load_row_groups(filesystem, dataset_path,
                                     footer_cache=footer_cache)
        if not all_pieces:
            raise NoDataAvailableError('No row groups found at {}'.format(dataset_path))
        pieces, worker_predicate, filters_predicate = self._filter_row_groups(
            filesystem, all_pieces, stored_schema, predicate, rowgroup_selector,
            filters, cur_shard, shard_count, footer_cache)
        del all_pieces
        if not pieces:
            raise NoDataAvailableError(
                'No row groups left after predicate/selector/shard filtering at '
                '{}'.format(dataset_path))
        self._pieces = pieces

        # -- ventilation (reference reader.py:622-637) -------------------------
        items = []
        for piece_index in range(len(pieces)):
            piece_predicate = worker_predicate
            if filters_predicate is not None:
                specialized = filters_predicate.specialize(pieces[piece_index],
                                                           stored_schema)
                if specialized is not None:
                    if piece_predicate is not None:
                        piece_predicate = in_reduce(
                            [piece_predicate, specialized], all)
                    else:
                        piece_predicate = specialized
            for drop_partition in range(shuffle_row_drop_partitions):
                items.append({'piece_index': piece_index,
                              'worker_predicate': piece_predicate,
                              'shuffle_row_drop_partition': (
                                  drop_partition, shuffle_row_drop_partitions)})
        # The in-flight bound must cover every worker's prefetch window or
        # the ventilator starves the readahead: each worker holds its current
        # item plus up to `lookahead` hinted ones.
        io_readahead = _validate_io_readahead(io_readahead)
        if io_readahead and not getattr(pool, 'supports_prefetch_hints', False):
            # a pool that never hints (dummy) would record every read as a
            # readahead miss — misleading diagnostics plus dead threads
            logger.debug('io_readahead disabled: %s does not hint workers '
                         'about upcoming items', type(pool).__name__)
            io_readahead = 0
        if io_readahead:
            from petastorm_tpu.readers.readahead import AUTO_MAX_DEPTH
            lookahead = (AUTO_MAX_DEPTH if io_readahead == 'auto'
                         else io_readahead)
        else:
            lookahead = 0
        self._io_readahead = io_readahead
        # -- sample lineage (see docs/lineage.md) ------------------------------
        import hashlib
        dataset_digest = hashlib.md5(
            str(dataset_path).encode()).hexdigest()[:12]
        #: The reader's :class:`~petastorm_tpu.lineage.LineageTracker`:
        #: per-item provenance records, per-epoch ventilated/delivered
        #: ledgers, quarantine ring. ``reader.lineage.coverage_report()``
        #: audits delivery; disabled (but present) under
        #: ``PETASTORM_TPU_LINEAGE=0``.
        self.lineage = LineageTracker(
            enabled=lineage_enabled(),
            dataset_digest=dataset_digest,
            shard=cur_shard if cur_shard is not None else -1,
            pieces=[(p.path, p.row_group, p.num_rows) for p in pieces],
            items=[(it['piece_index'],
                    tuple(it['shuffle_row_drop_partition'])) for it in items],
            row_filtered=(worker_predicate is not None
                          or filters_predicate is not None),
            # ventilate timestamps anchor the end-to-end latency histogram;
            # only stamped when the latency plane actually consumes them
            record_vent_ts=getattr(pool.stats, 'latency', None) is not None)
        #: End-to-end latency recording at ITEM delivery (one observation per
        #: registered item). A JaxDataLoader defers this to its own batch
        #: delivery point via :meth:`_defer_e2e_to_loader` so each delivered
        #: unit is observed exactly once.
        self._e2e_live = (self.lineage.enabled
                          and getattr(pool.stats, 'latency', None) is not None)
        self._last_e2e_seq = None
        self._worker_class = worker_class
        self._replay_items = {
            (it['piece_index'], tuple(it['shuffle_row_drop_partition'])): it
            for it in items}

        tracer = getattr(pool, 'tracer', None)
        ventilate_fn = pool.ventilate
        if self.lineage.enabled:
            # the ventilation ledger is the audit's "expected" side: what was
            # dispatched but never delivered is a DROP, not a mystery
            record_ventilated = self.lineage.record_ventilated
            inner_ventilate = ventilate_fn

            def ventilate_fn(*v_args, **v_kwargs):
                record_ventilated(
                    v_kwargs.get('epoch', 0), v_kwargs.get('piece_index'),
                    v_kwargs.get('shuffle_row_drop_partition', (0, 1)))
                inner_ventilate(*v_args, **v_kwargs)
        if tracer is not None:
            traced_ventilate = ventilate_fn

            def ventilate_fn(*v_args, **v_kwargs):
                with tracer.span('ventilate', 'ventilator'):
                    traced_ventilate(*v_args, **v_kwargs)
        self._ventilator = ConcurrentVentilator(
            ventilate_fn, items, iterations=num_epochs,
            randomize_item_order=shuffle_row_groups, random_seed=seed,
            max_ventilation_queue_size=(
                pool.workers_count * (1 + lookahead) + _VENTILATE_EXTRA_ROWGROUPS),
            heartbeat=self.health.beat if self.health.enabled else None,
            epoch_key='epoch')

        # the controller owns the readahead knob when autotune is on: the
        # machinery is constructed (dormant at depth 0) even when the reader
        # starts with readahead off, and 'auto' stops self-tuning locally —
        # two controllers on one knob would oscillate (docs/autotune.md)
        autotune_active = (autotune_options is not None
                           and self._pool_type in ('thread', 'process'))
        if autotune_options is not None and not autotune_active:
            logger.warning('autotune disabled: the %s pool has no live '
                           'actuators', self._pool_type)

        # -- device-decode planning (docs/decode.md "Device-side decode") ------
        from petastorm_tpu.ops.decode import plan_device_decode
        device_decode_plans, device_decode_declined = plan_device_decode(
            view_schema,
            has_predicate=(worker_predicate is not None
                           or filters_predicate is not None),
            has_ngram=self.ngram is not None,
            decode_hints=decode_hints,
            transform_spec=transform_spec,
            transformed_schema=transformed_schema,
            batched_output=self._is_batched_reader,
            tolerant_decode=(on_decode_error != 'raise'),
            worker_supported=getattr(worker_class, 'supports_device_decode',
                                     False))
        #: name -> :class:`~petastorm_tpu.ops.decode.DeviceColumnPlan` for the
        #: columns workers ship raw (bytes-through); empty when the whole
        #: reader declined to the host decode matrix.
        self.device_decode_plans = device_decode_plans
        #: column name (or ``'*'`` for whole-reader reasons) -> why the device
        #: path declined; surfaced by ``infeed_diagnosis`` for triage.
        self.device_decode_declined = device_decode_declined
        # a device-flagged TransformSpec fuses into the loader's jitted
        # decode program instead of running on CPU workers; the worker-side
        # spec is nulled so the transform runs exactly once
        self._device_transform_spec = (
            transform_spec if (device_decode_plans and transform_spec is not None
                               and transform_spec.device) else None)
        self._device_decode_deferred = False
        worker_transform_spec = (None if self._device_transform_spec is not None
                                 else transform_spec)
        worker_args = {
            'trace': tracer is not None,
            'health': self.health.enabled,
            'lineage': self.lineage.enabled,
            'latency': getattr(pool.stats, 'latency', None) is not None,
            'readahead_controlled': autotune_active,
            # resolved dicts, or False for explicitly-off (a missing key
            # means "default" to the worker, which is not the same thing)
            'retry': retry_options if retry_options else False,
            'hedge': hedge_options if hedge_options else False,
            'remote_read': remote_read,
            'on_decode_error': on_decode_error,
            'shard': cur_shard if cur_shard is not None else -1,
            'filesystem_factory': filesystem_factory,
            'dataset_path': dataset_path,
            'schema': view_schema,
            'full_schema': stored_schema,
            'ngram': self.ngram,
            'split_pieces': pieces,
            'local_cache': cache,
            'transform_spec': worker_transform_spec,
            'transformed_schema': transformed_schema,
            'decode_hints': decode_hints,
            'device_decode_plans': device_decode_plans,
            'io_readahead': io_readahead,
        }
        self._worker_args = worker_args
        # fail fast on bad hints (workers rebuild these after unpickling)
        build_decode_overrides(stored_schema, decode_hints)
        pool.lineage = self.lineage
        pool.start(worker_class, worker_args, self._ventilator)
        if metrics_interval:
            # the reader-level snapshot folds in the roofline gauges once a
            # profile exists, so emitted series gain %-of-ceiling context
            self._metrics_emitter = MetricsEmitter(
                self._stats_snapshot, metrics_interval, metrics_out)
            self._metrics_emitter.start()

        # -- live health + SLO layer (see docs/health.md, docs/latency.md) -----
        if slo:
            from petastorm_tpu.latency import SLOMonitor
            self._slo = SLOMonitor(slo, snapshot_fn=self._stats_snapshot,
                                   latency=getattr(pool.stats, 'latency',
                                                   None))
        # -- autotune controller (see docs/autotune.md) ------------------------
        if autotune_active:
            from petastorm_tpu import profiler as _profiler
            from petastorm_tpu.autotune import (HostArbiter,
                                                PipelineController,
                                                ReaderActuators, scratch_dir)
            from petastorm_tpu.readers.readahead import AUTO_INITIAL_DEPTH
            initial_depth = (AUTO_INITIAL_DEPTH if io_readahead == 'auto'
                             else int(io_readahead or 0))
            calibrate_mode = autotune_options['calibrate']
            calibration_schema = view_schema

            def calibration_fn():
                # probes (if any) run on the controller thread, never the
                # hot path; 'cached' never probes at all
                if not _profiler.profiler_enabled():
                    return None
                return _profiler.get_calibration(
                    self._filesystem_factory(), self._dataset_path,
                    self._pieces, calibration_schema, mode=calibrate_mode)

            self._controller = PipelineController(
                ReaderActuators(
                    pool, ventilator=self._ventilator,
                    pool_type=self._pool_type,
                    resize_timeout_s=float(
                        autotune_options['resize_timeout_s']),
                    initial_readahead=initial_depth),
                self._stats_snapshot,
                calibration_fn=calibration_fn,
                latency=getattr(pool.stats, 'latency', None),
                slo_targets=slo or {},
                options=autotune_options,
                arbiter=HostArbiter(
                    scratch_dir(autotune_options),
                    cpu_count=os.cpu_count() or 1,
                    tick_interval_s=autotune_options['tick_interval_s']))
            self._controller.start()
        pool_heartbeats = getattr(pool, 'heartbeats', None)
        if pool_heartbeats is not None:
            self.health.add_source(pool_heartbeats)
        resolved_debug_port = resolve_debug_port(debug_port)
        if stall_timeout or resolved_debug_port is not None:
            # on-demand verdicts (/healthz) use the default threshold when no
            # stall_timeout was configured; the background thread only runs
            # when one was (it exists to fire the flight recorder and to
            # cadence the SLO burn accounting)
            self._watchdog = PipelineWatchdog(
                self.health.heartbeats, pool.stats.snapshot,
                stall_after_s=stall_timeout or DEFAULT_STALL_AFTER_S,
                on_stall=self._on_stall, slo_monitor=self._slo)
            if stall_timeout:
                self._watchdog.start()
        if resolved_debug_port is not None:
            from petastorm_tpu.goodput import goodput_enabled
            from petastorm_tpu.podobs import podobs_enabled
            from petastorm_tpu.profiler import profiler_enabled
            observe_fn = None
            podmetrics_fn = None
            if podobs_enabled():
                # pod observability plane (docs/pod_observability.md): this
                # host's one-JSON snapshot on /observe/snapshot, and — when
                # the env names a pod peer list — the aggregated /podmetrics
                from petastorm_tpu.podobs import (PodObserver,
                                                  make_observe_fn,
                                                  pod_peers_from_env)
                observe_fn = make_observe_fn(
                    snapshot_fn=self._stats_snapshot,
                    health_fn=self._watchdog.evaluate,
                    slo_fn=(self._slo.evaluate if self._slo is not None
                            else None),
                    coverage_fn=(self.lineage.coverage_report
                                 if self.lineage.enabled else None),
                    cache_counters_fn=getattr(cache, 'host_counters', None),
                    span_tail_fn=(tracer.tail if tracer is not None
                                  else None),
                    goodput_fn=(self._goodput_route if goodput_enabled()
                                else None))
                pod_peers = pod_peers_from_env()
                if pod_peers:
                    podmetrics_fn = PodObserver(pod_peers).report
            self._debug_server = DebugServer(
                self._watchdog.evaluate, self._stats_snapshot,
                self.health.heartbeats, port=resolved_debug_port,
                coverage_fn=(self.lineage.coverage_report
                             if self.lineage.enabled else None),
                profile_fn=(self._profile_route if profiler_enabled()
                            else None),
                slo_fn=(self._slo.evaluate if self._slo is not None
                        else None),
                autotune_fn=(self._controller.report
                             if self._controller is not None else None),
                observe_fn=observe_fn,
                podmetrics_fn=podmetrics_fn,
                goodput_fn=(self._goodput_route if goodput_enabled()
                            else None))
            try:
                self._debug_server.start()
            except (OSError, OverflowError) as e:   # taken / out-of-range port
                # A taken port must not kill the pipeline it observes: with
                # PETASTORM_TPU_DEBUG_PORT set job-wide, the SECOND reader in
                # the job would otherwise crash at construction. The watchdog
                # stays armed; only this reader's endpoint is missing.
                logger.warning(
                    'debug endpoint disabled: could not bind 127.0.0.1:%d '
                    '(%s); pass debug_port=0 for an ephemeral port per '
                    'reader', resolved_debug_port, e)
                self._debug_server = None
        self._results_reader = results_reader_factory(transformed_schema,
                                                      self.ngram,
                                                      lineage=self.lineage)
        self._stopped = False
        #: True when every published NGram item is a columnar
        #: :class:`~petastorm_tpu.ngram.NGramWindowChunk` (no per-row
        #: predicate/transform/filters work item exists) — the JAX loader's
        #: vectorized collation path keys off this.
        self.ngram_chunked = (self.ngram is not None
                              and transform_spec is None
                              and worker_predicate is None
                              and filters_predicate is None)

    @property
    def batched_output(self) -> bool:
        return self._is_batched_reader

    # -- filtering -------------------------------------------------------------

    def _filter_row_groups(self, filesystem, pieces, stored_schema, predicate,
                           rowgroup_selector, filters, cur_shard, shard_count,
                           footer_cache=None):
        # Row-group indexes (rowgroup_selector) are built over the full
        # load_row_groups() ordering; carry each piece's original ordinal so
        # selection stays aligned after predicate/filters pruning.
        indexed = list(enumerate(pieces))
        worker_predicate = None
        filters_predicate = None
        partition_keys = (set(pieces[0].partition_dict.keys()) if pieces else set())
        if predicate is not None:
            predicate_fields = set(predicate.get_fields())
            unknown = predicate_fields - set(stored_schema.fields.keys())
            if unknown:
                raise ValueError('Predicate uses unknown fields: {}'.format(sorted(unknown)))
            if predicate_fields and predicate_fields <= partition_keys:
                # Evaluate on partition values only: prune pieces with no reads
                # (reference reader.py:577-608).
                indexed = [(i, p) for i, p in indexed if predicate.do_include(
                    {f: _cast_partition(stored_schema, f, p.partition_dict[f])
                     for f in predicate_fields})]
            else:
                worker_predicate = predicate

        conjunctions = normalize_filters(filters) if filters is not None else None
        if conjunctions:
            filter_cols = set(filter_column_names(conjunctions))
            # hive partition columns may be absent from the stored schema
            unknown = filter_cols - set(stored_schema.fields.keys()) - partition_keys
            if unknown:
                raise ValueError('filters use unknown columns: {}'.format(
                    sorted(unknown)))
            validate_filter_types(conjunctions, stored_schema, partition_keys)
            # Planning: exact on partition values, conservative on row-group
            # min/max statistics (reference delegates both to pyarrow,
            # reader.py:399-401). Pruning never decides inclusion on its own —
            # any non-partition term also pushes the full DNF down to the
            # workers so the result is row-exact. The partition-only pass runs
            # first so footers are only fetched for pieces it cannot prune.
            stats = RowGroupStatsEvaluator(filesystem, stored_schema,
                                           preloaded_footers=footer_cache)
            indexed = [(i, p) for i, p in indexed
                       if stats.piece_maybe_matches(p, conjunctions,
                                                    partition_only=True)]
            if filter_cols - partition_keys:
                stats.prefetch_footers({p.path for _, p in indexed})
                indexed = [(i, p) for i, p in indexed
                           if stats.piece_maybe_matches(p, conjunctions)]
                # row-exact residual; specialized per piece at ventilation
                # time (partition terms are constants for a given piece, and
                # may name columns the stored schema doesn't even declare)
                filters_predicate = FiltersPredicate(conjunctions)

        if rowgroup_selector is not None:
            from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
            indexes = get_row_group_indexes(filesystem, self._dataset_path)
            missing = set(rowgroup_selector.get_index_names()) - set(indexes.keys())
            if missing:
                raise ValueError('Selector references unknown indexes: {}'.format(
                    sorted(missing)))
            selected = rowgroup_selector.select_row_groups(indexes)
            indexed = [(i, p) for i, p in indexed if i in selected]

        pieces = [p for _, p in indexed]
        if cur_shard is not None:
            if len(pieces) < shard_count:
                # Fail loudly like the reference (reader.py:547-549): a
                # silently empty shard surprises users — and in SPMD training
                # it deadlocks the collectives of every other host.
                raise NoDataAvailableError(
                    'Dataset has only {} row groups after pruning but {} '
                    'shards were requested; some shards would receive no '
                    'data'.format(len(pieces), shard_count))
            pieces = [p for i, p in enumerate(pieces) if i % shard_count == cur_shard]
        return pieces, worker_predicate, filters_predicate

    # -- iteration -------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        try:
            row = self._results_reader.read_next(self._pool)
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration
        if self._e2e_live:
            # one end-to-end observation per delivered ITEM (row readers
            # yield many rows per item: record on the seq edge only)
            seq = self._results_reader.last_seq
            if seq is not None and seq != self._last_e2e_seq:
                self._last_e2e_seq = seq
                ts = self.lineage.ventilated_ts(seq)
                if ts is not None:
                    self._pool.stats.record_latency(
                        'e2e_batch', time.perf_counter() - ts)
        if self.device_decode_plans and not self._device_decode_deferred:
            # no loader claimed the raw columns: keep the "reader yields
            # decoded batches" contract by decoding on the host here (the
            # vectorized reference path, counted as batched host decode)
            row = self._host_decode_raw(row)
        return row

    def _host_decode_raw(self, batch):
        """Host-decode a bytes-through batch's raw planned columns (and run
        a device-flagged transform on the host) — the fallback consumer path
        when :meth:`_defer_device_decode_to_loader` was never called."""
        from petastorm_tpu.ops.decode import decode_raw_host
        updates = {}
        rows = 0
        for name, plan in self.device_decode_plans.items():
            col = getattr(batch, name, None)
            if col is None:
                continue
            updates[name] = decode_raw_host(plan, col)
            # per decoded COLUMN, matching the worker batched path and the
            # device counters — the fractions divide like-for-like
            rows += len(col)
        if updates:
            batch = batch._replace(**updates)
            self._pool.stats.add('rows_decoded_batched', rows)
        if self._device_transform_spec is not None:
            from petastorm_tpu.transform import apply_columnar_transform
            columns = apply_columnar_transform(self._device_transform_spec,
                                               self.schema, batch._asdict())
            batch = batch._replace(**columns)
        return batch

    def _defer_e2e_to_loader(self):
        """Called by ``JaxDataLoader`` when it takes over end-to-end latency
        recording at its own (later) batch-delivery point — the reader's
        per-item recording stops so each delivered unit is observed once."""
        self._e2e_live = False

    def _defer_device_decode_to_loader(self):
        """Called by ``JaxDataLoader`` (and the sharded staging path) when it
        claims the bytes-through columns: raw ``(n, stride)`` uint8 grids pass
        through :meth:`__next__` undecoded and the loader decodes them under
        ``jax.jit`` (fused with any device ``TransformSpec``). Returns
        ``(plans, device_transform_spec)``."""
        self._device_decode_deferred = True
        return self.device_decode_plans, self._device_transform_spec

    def next(self):
        return self.__next__()

    def iter_ngram_chunks(self):
        """Yield raw :class:`~petastorm_tpu.ngram.NGramWindowChunk`s (one per
        row-group work item) instead of per-window namedtuples — the
        zero-per-window-Python feed for vectorized batch collation. Only
        available when :attr:`ngram_chunked`; do not interleave with
        ``next()`` on the same pass."""
        if not self.ngram_chunked:
            # plain method (not a generator) so misuse fails HERE, not at the
            # consumer's first next() in some other component
            raise RuntimeError(
                'iter_ngram_chunks() needs a chunk-mode NGram reader (no '
                'predicate/transform_spec/filters); iterate per-window '
                'instead')

        def chunks():
            while True:
                try:
                    yield self._results_reader.read_next_chunk(self._pool)
                except EmptyResultError:
                    self.last_row_consumed = True
                    return
        return chunks()

    def drain(self):
        """Consume the rest of the stream WITHOUT decoding/collating on the
        consumer side (published items are discarded as-is), leaving the
        reader resettable. Used by the sharded loader's lockstep stop: a host
        whose shard has surplus batches discards them raw instead of paying
        window/batch assembly for data nobody reads."""
        discard = getattr(self._results_reader, 'discard_buffered', None)
        if discard is not None:
            discard()
        tracker = self.lineage if self.lineage.enabled else None
        try:
            while True:
                # register discarded items' provenance so the coverage audit
                # still sees them delivered (dropped-on-purpose != dropped)
                unwrap_envelope(self._pool.get_results(), tracker)
        except EmptyResultError:
            self.last_row_consumed = True

    def reset(self):
        """Restart iteration for another ``num_epochs`` pass; only legal after
        the previous pass fully drained (reference ``reader.py:468-492``)."""
        if not self.last_row_consumed:
            raise RuntimeError(
                'Reader.reset() is only supported after the previous epoch set was '
                'fully consumed (in-flight row groups cannot be recalled)')
        # epoch numbers are globally monotone (the ventilator never rewinds),
        # so the new pass audits against fresh per-epoch ledgers
        self.lineage.start_pass()
        self._ventilator.reset(self._num_epochs)
        self.last_row_consumed = False

    # -- flight recorder -------------------------------------------------------

    def _on_stall(self, verdict):
        if self._slo is not None:
            # edge-triggered upstream: one episode per stall, however long
            self._slo.record_stall_episode()
        try:
            path = self.dump_flight_record(verdict=verdict)
            logger.error('pipeline stalled; flight record written to %s', path)
        except Exception:
            logger.exception('failed to write flight record')

    def dump_flight_record(self, path=None, verdict=None):
        """Write a flight-recorder JSON (heartbeats, stats snapshot, queue
        occupancy, per-thread stacks, span ring tail when tracing is on) and
        return its path. The watchdog calls this automatically on a stall;
        call it directly for an on-demand dump. ``path=None`` names a file
        in ``flight_record_dir`` (or the system temp dir)."""
        if verdict is None:
            if self._watchdog is not None:
                verdict = self._watchdog.evaluate()
            else:
                from petastorm_tpu.health import classify_pipeline
                verdict = classify_pipeline(self.health.heartbeats(),
                                            self._pool.stats.snapshot())
        snapshot = self._pool.stats.snapshot()
        queues = {
            'queue_depth': snapshot.get('queue_depth', 0),
            'queue_depth_max': snapshot.get('queue_depth_max', 0),
            'shuffle_buffer_depth': snapshot.get('shuffle_buffer_depth', 0),
            'readahead_depth': snapshot.get('readahead_depth', 0),
            'prefetch_occupancy': snapshot.get('prefetch_occupancy', 0),
            'prefetch_occupancy_max': snapshot.get('prefetch_occupancy_max',
                                                   0),
        }
        roofline = None
        if self._last_profile is not None:
            from petastorm_tpu.profiler import roofline_summary
            roofline = roofline_summary(self._last_profile)
        latency_plane = getattr(self._pool.stats, 'latency', None)
        slo_verdict = None
        if self._slo is not None:
            try:
                slo_verdict = self._slo.evaluate()
            except Exception:
                logger.exception('SLO evaluation failed for flight record')
        record = build_flight_record(verdict, self.health.heartbeats(),
                                     snapshot, queues, tracer=self.tracer,
                                     lineage=(self.lineage.flight_summary()
                                              if self.lineage.enabled
                                              else None),
                                     roofline=roofline,
                                     latency=(latency_plane.flight_summary()
                                              if latency_plane is not None
                                              else None),
                                     slo=slo_verdict,
                                     autotune=(
                                         self._controller.flight_summary()
                                         if self._controller is not None
                                         else None),
                                     goodput=(
                                         self._goodput.flight_summary()
                                         if self._goodput is not None
                                         else None))
        if path is None:
            import tempfile
            out_dir = self._flight_record_dir or tempfile.gettempdir()
            path = os.path.join(out_dir, 'petastorm_tpu_flight_{}_{}.json'
                                .format(os.getpid(), int(time.time())))
        return write_flight_record(path, record)

    # -- goodput plane (see docs/goodput.md) -----------------------------------

    def register_goodput(self, monitor):
        """Attach a loader's :class:`~petastorm_tpu.goodput.GoodputMonitor`
        so the reader's surfaces (``/goodput``, ``/diagnostics``, flight
        records, the pod observe snapshot) serve its per-step accounting.
        The JAX loaders call this at construction; latest registration
        wins (one live consumer loop per reader)."""
        self._goodput = monitor

    def _goodput_route(self):
        """``GET /goodput`` source: the monitor's summary once a loader
        registered one, else an explicit not-yet-attached marker (the
        plane is on — a 404 would read as kill-switched)."""
        if self._goodput is None:
            return {'enabled': True, 'attached': False}
        return self._goodput.summary()

    # -- roofline profiler (see docs/profiling.md) -----------------------------

    def _stats_snapshot(self):
        """The pool's stats snapshot plus the roofline gauges of the most
        recent :meth:`profile` call (``stage_ceiling_*``,
        ``roofline_fraction``, ``binding_stage``) — what the metrics
        emitter and the debug endpoint's ``/metrics`` serve, so scrapes
        show %-of-ceiling, not just raw samples/s."""
        snapshot = self._pool.stats.snapshot()
        # derived decode-path mix (docs/decode.md): scrapes and flight
        # records should answer "is the device path actually carrying the
        # decode" without re-deriving it from raw counters
        from petastorm_tpu.workers.stats import device_decode_fraction
        fraction = device_decode_fraction(snapshot)
        if fraction is not None:
            snapshot['device_decode_fraction'] = fraction
        if self._roofline_gauges:
            snapshot.update(self._roofline_gauges)
        if self._controller is not None:
            snapshot.update(self._controller.gauges())
        return snapshot

    def profile(self, calibrate='auto', sample_row_groups: int = 3,
                samples_per_sec=None):
        """The roofline profile of this reader right now: measured rate vs
        the calibrated per-stage ceilings of *this host on this dataset*,
        the binding stage, overlap-aware span attribution, and the what-if
        advisor's ranked knob recommendations.

        ``calibrate`` picks how ceilings are obtained: ``'cached'`` only
        loads a previously saved calibration artifact (cheap, never
        probes), ``'auto'`` (default) probes on a cache miss, ``'force'``
        always re-probes. Probes run on the calling thread against sampled
        row groups — seconds of work, on demand, never on the hot path.
        ``samples_per_sec`` overrides the measured rate when the caller
        measured it directly (benchmarks do); otherwise it is estimated
        from the stats window's items/s times the calibrated mean rows per
        row group. See ``docs/profiling.md``."""
        from petastorm_tpu import profiler
        if not profiler.profiler_enabled():
            raise RuntimeError('the roofline profiler is disabled via {}=0'
                               .format(profiler.PROFILER_ENV_VAR))
        # calibrate against the reader's VIEW schema, not the stored one: a
        # column-pruned reader only pays for the columns it decodes, and
        # the digest carries the view so differently-pruned readers over
        # one store never share a calibration artifact
        calibration = profiler.get_calibration(
            self._filesystem_factory(), self._dataset_path, self._pieces,
            self._worker_args['schema'], mode=calibrate,
            sample_row_groups=sample_row_groups)
        spans = self.tracer.spans() if self.tracer is not None else None
        result = profiler.build_profile(
            self._pool.stats.snapshot(), calibration, spans=spans,
            samples_per_sec=samples_per_sec,
            workers_count=self._pool.workers_count,
            io_readahead=self._io_readahead, pool_type=self._pool_type,
            cache_type=self._cache_type)
        self._last_profile = result
        self._roofline_gauges = profiler.roofline_gauges(result)
        return result

    def explain_throughput(self, calibrate='auto') -> str:
        """One sentence: "measured X samples/s = Y% of the binding stage's
        ceiling Z", plus the advisor's top recommendations. Runs
        :meth:`profile` (probing on a calibration-cache miss unless
        ``calibrate='cached'``)."""
        from petastorm_tpu import profiler
        return profiler.explain(self.profile(calibrate=calibrate))

    def _profile_route(self):
        """``GET /profile`` source. An HTTP probe must stay cheap: serve
        the most recent :meth:`profile` result when one exists (periodic
        scrapers must not recompute the dataset digest and span-union
        attribution per request), and only build a fresh cached-calibration
        profile (never probing) before the first ``profile()`` call."""
        if self._last_profile is not None:
            return dict(self._last_profile, from_cache=True)
        fresh = self.profile(calibrate='cached')
        if not fresh.get('calibrated'):
            # don't pin an uncalibrated snapshot: the route stays live
            # until a calibration exists, then starts serving the cache
            self._last_profile = None
            self._roofline_gauges = {}
        return fresh

    # -- lineage (see docs/lineage.md) -----------------------------------------

    @property
    def last_seq(self):
        """Tracker seq of the most recently yielded item (``None`` until the
        first yield or when lineage is off)."""
        return getattr(self._results_reader, 'last_seq', None)

    @property
    def last_row_offset(self):
        """Payload-row offset of the most recently yielded ROW within its
        published item (row readers only; ``None`` for batched output)."""
        return getattr(self._results_reader, 'last_row_offset', None)

    @property
    def last_provenance(self):
        """:class:`~petastorm_tpu.lineage.Provenance` of the most recently
        yielded item/batch (``None`` before the first yield, when lineage is
        off, or after ring eviction)."""
        return self.lineage.resolve(self.last_seq)

    def explain_batch(self, batch=None):
        """Human-readable provenance of a batch.

        ``batch=None`` explains the most recently yielded reader item (for
        batched readers that IS the batch: one row group). A loader batch
        dict carrying ``'_provenance'`` (or a
        :class:`~petastorm_tpu.lineage.BatchProvenance` directly) resolves
        per-row: every distinct source row group with its row count,
        selection and shuffle quality."""
        if batch is None:
            record = self.last_provenance
            if record is None:
                return {'enabled': self.lineage.enabled, 'sources': []}
            return {'enabled': True, 'rows': record.rows,
                    'sources': [dict(record._asdict(),
                                     selection=list(record.selection))]}
        if isinstance(batch, dict):
            batch = batch_provenance_of(batch) or batch
        if isinstance(batch, BatchProvenance):
            return dict(batch.summary(), enabled=True)
        raise TypeError('explain_batch needs None, a loader batch dict with '
                        "a '_provenance' entry, or a BatchProvenance; got "
                        '{!r}'.format(type(batch)))

    def replay(self, provenance):
        """Re-fetch the exact rows behind ``provenance`` (a
        :class:`~petastorm_tpu.lineage.Provenance` record, a registered seq,
        a ``BatchProvenance``, or a loader batch dict) through this reader's
        own row-group machinery. Returns a dict of numpy columns —
        bit-identical to the original delivery for deterministic
        decode/transform paths. See ``docs/lineage.md``."""
        return _lineage_replay(self, provenance)

    def audit(self) -> 'CoverageAuditor':
        """A :class:`~petastorm_tpu.lineage.CoverageAuditor` over this
        reader's ledgers (``audit().report()`` / ``assert_complete()``)."""
        return CoverageAuditor(self.lineage)

    # -- lifecycle -------------------------------------------------------------

    def stop(self):
        """Stop the pipeline. Idempotent, and ordered so the health layer
        (watchdog, emitter) is signalled even when the pool below died
        uncleanly: an unclean pool must never leave monitoring threads
        running against a corpse."""
        self._stopped = True
        if self._controller is not None:
            # signal the controller before the pool goes down: a tick that
            # lands mid-teardown must find the stop event, not a corpse
            self._controller.stop(join=False)
        if self._metrics_emitter is not None:
            self._metrics_emitter.stop(join=False)
        if self._watchdog is not None:
            self._watchdog.stop(join=False)
        try:
            self._pool.stop()
        finally:
            if self._debug_server is not None:
                self._debug_server.stop()

    def join(self):
        """Join every pipeline thread: the pool, then the metrics emitter,
        watchdog and debug server (all with bounded joins). Idempotent —
        every stop below tolerates being called again — so teardown paths
        that cannot know whether an earlier join ran may call it anyway."""
        # the controller joins FIRST: a tick actuating mid-join would race
        # the pool's socket teardown below
        if self._controller is not None:
            self._controller.stop()
        try:
            self._pool.join()
        finally:
            if self._metrics_emitter is not None:
                # joins the emitter thread and writes one final snapshot, so
                # even sub-interval runs record at least one sample
                self._metrics_emitter.stop()
            if self._watchdog is not None:
                self._watchdog.stop()
            if self._debug_server is not None:
                self._debug_server.stop()
        if self._trace_export and self.tracer is not None:
            try:
                self.tracer.export_chrome_trace(self._trace_export)
            except OSError:
                logger.exception('Failed to export chrome trace to %s',
                                 self._trace_export)

    def cleanup(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()

    @property
    def stats(self):
        """The pool's :class:`~petastorm_tpu.workers.stats.ReaderStats` —
        the live per-stage telemetry accumulator. The JAX loaders record
        device staging time into it; ``diagnostics`` snapshots it."""
        return getattr(self._pool, 'stats', None)

    @property
    def slo(self):
        """The reader's :class:`~petastorm_tpu.latency.SLOMonitor` (``None``
        unless built with ``slo=dict(...)``). ``reader.slo.evaluate()`` is
        the on-demand verdict the ``/slo`` route serves."""
        return self._slo

    @property
    def autotune(self):
        """The reader's
        :class:`~petastorm_tpu.autotune.PipelineController` (``None``
        unless autotune resolved on — ``autotune=`` kwarg or
        ``PETASTORM_TPU_AUTOTUNE=1``, minus the kill switch).
        ``reader.autotune.report()`` is what ``/autotune`` serves."""
        return self._controller

    @property
    def latency(self):
        """The pool's :class:`~petastorm_tpu.latency.PipelineLatency` — the
        per-stage streaming histograms (``None`` under the
        ``PETASTORM_TPU_LATENCY=0`` kill switch)."""
        return getattr(self._pool.stats, 'latency', None)

    @property
    def watchdog(self):
        """The reader's :class:`~petastorm_tpu.health.PipelineWatchdog`
        (``None`` unless built with ``stall_timeout=`` or ``debug_port=``).
        ``reader.watchdog.evaluate()`` classifies the pipeline right now."""
        return self._watchdog

    @property
    def debug_port(self):
        """The bound port of the HTTP debug endpoint (``None`` when no
        server runs; differs from the requested port when that was 0)."""
        return self._debug_server.port if self._debug_server is not None \
            else None

    @property
    def tracer(self):
        """The pool's :class:`~petastorm_tpu.tracing.Tracer` (``None`` unless
        the reader was built with ``trace=``/``PETASTORM_TPU_TRACE``). Call
        ``reader.tracer.export_chrome_trace(path)`` for a Perfetto-loadable
        timeline; the JAX loaders record their spans into the same tracer."""
        return getattr(self._pool, 'tracer', None)

    @property
    def diagnostics(self):
        """Pool accounting plus a :class:`ReaderStats` snapshot: per-stage
        wall times (``worker_io_s``/``worker_decode_s``/``serialize_s``/
        ``deserialize_s``/``queue_wait_s``/``device_stage_s``), payload
        bytes/copies/frames, and queue-occupancy gauges."""
        return dict(self._pool.diagnostics)


def _cast_partition(schema, field_name, value):
    field = schema.fields.get(field_name)
    return cast_partition_value(field.numpy_dtype if field is not None else None, value)
