"""Ventilator: feeds work items to a pool with epoch control, per-epoch
reshuffling and in-flight back-pressure.

Reference parity: ``petastorm/workers_pool/ventilator.py`` — ``Ventilator`` ABC
(:26-52), ``ConcurrentVentilator`` (:55-166).

Deviation: shuffling uses a seedable ``np.random.Generator`` so epoch order is
reproducible and checkpointable (the reference notes deterministic ordering
"enables implementing piece shuffling given a seed",
``etl/dataset_metadata.py:274-278`` — we actually do it).
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from typing import List, Optional

import numpy as np


class Ventilator(ABC):
    """Base class for ventilators which put work items into a pool."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    @abstractmethod
    def start(self):
        """Begin ventilating."""

    @abstractmethod
    def processed_item(self):
        """Called by the pool whenever a ventilated item completed processing."""

    @abstractmethod
    def completed(self) -> bool:
        """True if all items (over all epochs) have been ventilated."""

    @abstractmethod
    def stop(self):
        """Stop ventilating."""


class BackPressuredVentilator(Ventilator):
    """Shared machinery for daemon-thread ventilators with bounded in-flight
    items: slot accounting, stop/done events, thread lifecycle. Subclasses
    implement :meth:`_ventilate_loop`, calling :meth:`_acquire_slot` before
    each :attr:`_ventilate_fn` call and returning when done (or when
    ``_acquire_slot`` returns False on stop)."""

    def __init__(self, ventilate_fn, max_in_flight: int,
                 interval_s: float = 0.01, heartbeat=None):
        super().__init__(ventilate_fn)
        self._max_in_flight = max_in_flight
        self._interval = interval_s
        #: Optional ``heartbeat(entity, stage)`` callable (the reader's
        #: ``HealthMonitor.beat``). Stage ``ventilate`` is active work;
        #: ``backpressured`` (blocked on the in-flight bound) and ``done``
        #: are idle-class stages — see ``health.IDLE_STAGES`` (a stalled
        #: consumer must indict the wedged entity, not the ventilator that
        #: is correctly waiting on it).
        self._heartbeat = heartbeat
        self._in_flight = 0
        # Condition, not a sleep-poll: a fixed poll period caps ventilation at
        # ~1/interval items/sec, which throttles the whole pipeline once row
        # groups are consumed faster than that (small-row-group stores hit
        # this). processed_item() notifies, so a freed slot is re-filled
        # immediately; the timeout below only bounds stop-latency.
        self._slot_cv = threading.Condition()
        self._paused = False
        self._stop_event = threading.Event()
        self._completed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='petastorm-tpu-ventilator')
        self._thread.start()

    def _run(self):
        self._beat('ventilate')
        self._ventilate_loop()
        self._completed.set()
        self._beat('done')

    def _beat(self, stage):
        if self._heartbeat is not None:
            self._heartbeat('ventilator', stage)

    def _ventilate_loop(self):
        raise NotImplementedError

    def _acquire_slot(self) -> bool:
        """Block until an in-flight slot frees up; False if stopped."""
        first_wait = True
        with self._slot_cv:
            while not self._stop_event.is_set():
                if not self._paused and self._in_flight < self._max_in_flight:
                    self._in_flight += 1
                    self._beat('ventilate')
                    return True
                if first_wait:
                    # beat once per back-pressure episode, not per poll tick
                    first_wait = False
                    self._beat('backpressured')
                self._slot_cv.wait(timeout=self._interval)
        return False

    def processed_item(self):
        with self._slot_cv:
            self._in_flight -= 1
            self._slot_cv.notify()

    # -- live actuation (the autotune controller's knobs; docs/autotune.md) ----

    @property
    def max_in_flight(self) -> int:
        """Current in-flight bound (the live ventilation window)."""
        with self._slot_cv:
            return self._max_in_flight

    def set_max_in_flight(self, bound: int) -> None:
        """Live-adjust the in-flight bound. Shrinking never recalls items
        already ventilated — the bound simply admits nothing new until
        enough complete; growing wakes a back-pressured ventilator
        immediately."""
        if not isinstance(bound, int) or bound < 1:
            raise ValueError('max_in_flight must be a positive int, got '
                             '{!r}'.format(bound))
        with self._slot_cv:
            self._max_in_flight = bound
            self._slot_cv.notify_all()

    def pause(self) -> None:
        """Stop admitting new items (in-flight ones complete normally) —
        the quiesce half of the process pool's drain-then-retire shrink.
        Idempotent; the pipeline's completion accounting is unaffected
        (a paused mid-epoch ventilator never reads as completed)."""
        with self._slot_cv:
            self._paused = True

    def resume(self) -> None:
        """Undo :meth:`pause`; wakes the ventilator thread immediately."""
        with self._slot_cv:
            self._paused = False
            self._slot_cv.notify_all()

    @property
    def in_flight(self) -> int:
        """Items ventilated but not yet reported processed."""
        with self._slot_cv:
            return self._in_flight

    def completed(self) -> bool:
        # All items ventilated AND nothing still in flight.
        if not self._completed.is_set():
            return False
        with self._slot_cv:
            return self._in_flight == 0

    def fully_ventilated(self) -> bool:
        """True once every item was handed to the pool (some may be in flight)."""
        return self._completed.is_set()

    def stop(self):
        self._stop_event.set()
        self._completed.set()
        with self._slot_cv:
            self._slot_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)


class ConcurrentVentilator(BackPressuredVentilator):
    """Ventilates a fixed item list from a daemon thread.

    :param ventilate_fn: ``pool.ventilate``-compatible callable.
    :param items: list of kwargs-dicts (or arbitrary picklables) to ventilate.
    :param iterations: number of epochs; ``None`` means infinite.
    :param randomize_item_order: reshuffle items before each epoch.
    :param random_seed: seed for the reshuffle generator (``None`` = OS entropy).
    :param max_ventilation_queue_size: bound on in-flight (ventilated but not yet
        processed) items; back-pressure (reference ``ventilator.py:146-149``).
    :param ventilation_interval_s: poll period while back-pressured.
    :param heartbeat: optional ``heartbeat(entity, stage)`` callable; the
        ventilator thread publishes liveness as entity ``'ventilator'``
        (see :mod:`petastorm_tpu.health`).
    :param epoch_key: when set, each dict item is ventilated with an extra
        ``{epoch_key: current_epoch}`` kwarg so workers can stamp results
        with the epoch they belong to (the provenance layer's epoch source,
        see :mod:`petastorm_tpu.lineage`). Epoch numbers are globally
        monotone: :meth:`reset` continues counting, it never rewinds.
    """

    def __init__(self, ventilate_fn, items: List, iterations: Optional[int] = 1,
                 randomize_item_order: bool = False,
                 random_seed: Optional[int] = None,
                 max_ventilation_queue_size: Optional[int] = None,
                 ventilation_interval_s: float = 0.01,
                 start_epoch: int = 0,
                 heartbeat=None,
                 epoch_key: Optional[str] = None):
        if iterations is not None and iterations < 1:
            raise ValueError('iterations must be positive or None, got {}'.format(iterations))
        items = list(items)
        super().__init__(ventilate_fn,
                         max_in_flight=max_ventilation_queue_size or len(items) or 1,
                         interval_s=ventilation_interval_s,
                         heartbeat=heartbeat)
        self._items = items
        self._iterations_remaining = iterations
        self._randomize_item_order = randomize_item_order
        self._rng = np.random.default_rng(random_seed)
        self._random_seed = random_seed
        self._epoch = start_epoch
        self._epoch_key = epoch_key
        if not self._items:
            self._completed.set()

    @property
    def epoch(self) -> int:
        """Epochs fully ventilated so far (checkpointable progress marker)."""
        return self._epoch

    def _ventilate_loop(self):
        while not self._stop_event.is_set():
            if self._iterations_remaining is not None and self._iterations_remaining <= 0:
                break
            order = self._items
            if self._randomize_item_order:
                # Seeded per-epoch shuffle: epoch k order is reproducible from
                # (seed, k) which makes mid-training restarts deterministic.
                order = list(self._items)
                self._rng.shuffle(order)
            for item in order:
                if not self._acquire_slot():
                    return
                if isinstance(item, dict):
                    if self._epoch_key is not None:
                        item = dict(item, **{self._epoch_key: self._epoch})
                    self._ventilate_fn(**item)
                else:
                    self._ventilate_fn(item)
            self._epoch += 1
            if self._iterations_remaining is not None:
                self._iterations_remaining -= 1

    def reset(self, iterations: Optional[int] = 1):
        """Restart ventilation for more epochs; only legal after completion
        (reference ``ventilator.py:125-134``)."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError('Cannot reset a ventilator that has not completed')
        self._iterations_remaining = iterations
        self._stop_event.clear()
        self._completed.clear()
        if not self._items:
            self._completed.set()
        self._thread = None
        self.start()
