"""Ventilator: feeds work items to a pool with epoch control, per-epoch
reshuffling and in-flight back-pressure.

Reference parity: ``petastorm/workers_pool/ventilator.py`` — ``Ventilator`` ABC
(:26-52), ``ConcurrentVentilator`` (:55-166).

Deviation: shuffling uses a seedable ``np.random.Generator`` so epoch order is
reproducible and checkpointable (the reference notes deterministic ordering
"enables implementing piece shuffling given a seed",
``etl/dataset_metadata.py:274-278`` — we actually do it).
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from typing import List, Optional

import numpy as np


class Ventilator(ABC):
    """Base class for ventilators which put work items into a pool."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    @abstractmethod
    def start(self):
        """Begin ventilating."""

    @abstractmethod
    def processed_item(self):
        """Called by the pool whenever a ventilated item completed processing."""

    @abstractmethod
    def completed(self) -> bool:
        """True if all items (over all epochs) have been ventilated."""

    @abstractmethod
    def stop(self):
        """Stop ventilating."""


class ConcurrentVentilator(Ventilator):
    """Ventilates a fixed item list from a daemon thread.

    :param ventilate_fn: ``pool.ventilate``-compatible callable.
    :param items: list of kwargs-dicts (or arbitrary picklables) to ventilate.
    :param iterations: number of epochs; ``None`` means infinite.
    :param randomize_item_order: reshuffle items before each epoch.
    :param random_seed: seed for the reshuffle generator (``None`` = OS entropy).
    :param max_ventilation_queue_size: bound on in-flight (ventilated but not yet
        processed) items; back-pressure (reference ``ventilator.py:146-149``).
    :param ventilation_interval_s: poll period while back-pressured.
    """

    def __init__(self, ventilate_fn, items: List, iterations: Optional[int] = 1,
                 randomize_item_order: bool = False,
                 random_seed: Optional[int] = None,
                 max_ventilation_queue_size: Optional[int] = None,
                 ventilation_interval_s: float = 0.01,
                 start_epoch: int = 0):
        super().__init__(ventilate_fn)
        if iterations is not None and iterations < 1:
            raise ValueError('iterations must be positive or None, got {}'.format(iterations))
        self._items = list(items)
        self._iterations_remaining = iterations
        self._randomize_item_order = randomize_item_order
        self._rng = np.random.default_rng(random_seed)
        self._random_seed = random_seed
        self._max_queue_size = max_ventilation_queue_size or len(self._items) or 1
        self._interval = ventilation_interval_s
        self._epoch = start_epoch

        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._completed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if not self._items:
            self._completed.set()

    @property
    def epoch(self) -> int:
        """Epochs fully ventilated so far (checkpointable progress marker)."""
        return self._epoch

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        self._thread = threading.Thread(target=self._ventilate_loop, daemon=True,
                                        name='petastorm-tpu-ventilator')
        self._thread.start()

    def _ventilate_loop(self):
        while not self._stop_event.is_set():
            if self._iterations_remaining is not None and self._iterations_remaining <= 0:
                break
            order = self._items
            if self._randomize_item_order:
                # Seeded per-epoch shuffle: epoch k order is reproducible from
                # (seed, k) which makes mid-training restarts deterministic.
                order = list(self._items)
                self._rng.shuffle(order)
            for item in order:
                while not self._stop_event.is_set():
                    with self._in_flight_lock:
                        if self._in_flight < self._max_queue_size:
                            self._in_flight += 1
                            break
                    time.sleep(self._interval)
                if self._stop_event.is_set():
                    return
                self._ventilate_fn(**item) if isinstance(item, dict) else self._ventilate_fn(item)
            self._epoch += 1
            if self._iterations_remaining is not None:
                self._iterations_remaining -= 1
        self._completed.set()

    def processed_item(self):
        with self._in_flight_lock:
            self._in_flight -= 1

    def completed(self) -> bool:
        # All epochs ventilated AND nothing still in flight.
        if not self._completed.is_set():
            return False
        with self._in_flight_lock:
            return self._in_flight == 0

    def fully_ventilated(self) -> bool:
        """True once all epochs were handed to the pool (items may still be in flight)."""
        return self._completed.is_set()

    def reset(self, iterations: Optional[int] = 1):
        """Restart ventilation for more epochs; only legal after completion
        (reference ``ventilator.py:125-134``)."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError('Cannot reset a ventilator that has not completed')
        self._iterations_remaining = iterations
        self._stop_event.clear()
        self._completed.clear()
        if not self._items:
            self._completed.set()
        self._thread = None
        self.start()

    def stop(self):
        self._stop_event.set()
        self._completed.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
