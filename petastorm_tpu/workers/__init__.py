"""Worker-pool protocol types (reference ``petastorm/workers_pool/__init__.py:16-26``)."""


class EmptyResultError(Exception):
    """Raised by ``pool.get_results()`` when the result stream is exhausted."""


class TimeoutWaitingForResultError(Exception):
    """Raised when no result arrived within the configured timeout."""


class VentilatedItemProcessedMessage:
    """Control message a worker emits after fully processing one ventilated item.

    Drives the ventilated-vs-processed accounting that detects end of epoch
    (reference ``thread_pool.py:155-176``). ``stats`` optionally carries the
    item's per-stage wall times (``{stage: seconds}``) plus transport counters
    back across the process boundary; the pool merges it into ``pool.stats``.
    ``seq`` is the pool-assigned ventilation sequence number of the item
    (process pools; ``None`` elsewhere) — it retires the item from the
    pool's outstanding ledger, which is what worker auto-recovery consults
    to know exactly which in-flight items died with a crashed worker.
    """
    __slots__ = ('stats', 'seq')

    def __init__(self, stats=None, seq=None):
        self.stats = stats
        self.seq = seq
