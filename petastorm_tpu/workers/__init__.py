"""Worker-pool protocol types (reference ``petastorm/workers_pool/__init__.py:16-26``)."""


class EmptyResultError(Exception):
    """Raised by ``pool.get_results()`` when the result stream is exhausted."""


class TimeoutWaitingForResultError(Exception):
    """Raised when no result arrived within the configured timeout."""


class VentilatedItemProcessedMessage:
    """Control message a worker emits after fully processing one ventilated item.

    Drives the ventilated-vs-processed accounting that detects end of epoch
    (reference ``thread_pool.py:155-176``).
    """
    __slots__ = ()
