"""Worker protocol (reference ``petastorm/workers_pool/worker_base.py:18-35``)."""

import os
import threading
import time
from abc import ABC, abstractmethod

from petastorm_tpu.latency import LatencyDeltas


class WorkerBase(ABC):
    """A worker processes ventilated items and emits 0..n results via
    ``publish_func``. One instance lives per thread/process."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args
        #: Per-stage wall time accumulated since the last drain; the owning
        #: pool drains it after each processed item (thread pools merge it
        #: straight into ``pool.stats``, process pools ship it back in the
        #: accounting control message).
        self.stage_times = {}
        #: Monotonic counters / last-value gauges accumulated since the last
        #: drain (e.g. readahead hit/miss, prefetch-queue occupancy); same
        #: drain discipline as :attr:`stage_times`.
        self.stat_counts = {}
        self.stat_gauges = {}
        #: Span tuples accumulated since the last drain (see
        #: :mod:`petastorm_tpu.tracing`); recorded only when the pool enabled
        #: tracing via ``worker_args['trace']``, drained like the stats.
        self.trace_spans = []
        self.tracing_enabled = isinstance(args, dict) and bool(args.get('trace'))
        self._trace_pid = os.getpid()
        #: Per-entity heartbeat records: ``entity -> (stage, ts, items)``
        #: where ``ts`` is ``time.perf_counter()``. The worker's own entity
        #: (``worker-<id>``) beats via :meth:`beat`; auxiliary threads it
        #: owns (the readahead reader) beat their own entity via
        #: :meth:`beat_entity`. Thread/dummy pools read this dict live;
        #: process workers ship :meth:`heartbeat_snapshot` back in the
        #: accounting message and a low-frequency heartbeat frame. Each beat
        #: replaces a whole tuple, so cross-thread reads are safe.
        self.heartbeats = {}
        self.health_enabled = not (isinstance(args, dict)
                                   and args.get('health') is False)
        #: Sample-lineage publication gate (see
        #: :mod:`petastorm_tpu.lineage`): when set, piece workers wrap each
        #: published payload in a provenance envelope and quarantine records
        #: accumulate here until the owning pool drains them (accounting
        #: message for process pools, direct merge for in-process pools).
        self.lineage_enabled = isinstance(args, dict) and bool(args.get('lineage'))
        #: Worker-side tail-latency accumulator (``None`` under the
        #: ``PETASTORM_TPU_LATENCY=0`` kill switch): observations are
        #: bucketed locally against the fixed geometric bounds and drained
        #: as compact ``{stage: bucket-delta}`` dicts — process pools ship
        #: them in the accounting control message exactly like the stage
        #: times, so a dead worker loses only unshipped deltas.
        self.latency = (LatencyDeltas()
                        if isinstance(args, dict) and args.get('latency')
                        else None)
        self.quarantine_records = []
        self.empty_publishes = []
        self._entity = 'worker-{}'.format(worker_id)
        self._items_done = 0
        if self.health_enabled:
            self.beat('starting')

    @abstractmethod
    def process(self, *args, **kwargs):
        """Process one ventilated work item; call ``self.publish_func(result)``
        zero or more times."""

    def record_time(self, stage: str, seconds: float) -> None:
        """Accumulate ``seconds`` of wall time against a pipeline stage
        (see :mod:`petastorm_tpu.workers.stats` for the stage names). Also
        counts as a heartbeat: finishing a timed stage is progress."""
        self.stage_times[stage] = self.stage_times.get(stage, 0.0) + seconds
        if self.latency is not None:
            # one histogram observation per timed section (io read, decode
            # pass) — per-observation durations, not the per-item sum
            self.latency.record_time_stage(stage, seconds)
        if self.health_enabled:
            self.beat(stage[:-2] if stage.endswith('_s') else stage)

    # -- heartbeats ------------------------------------------------------------

    def beat(self, stage: str) -> None:
        """Publish a heartbeat for this worker's own entity: it is now in
        ``stage`` (e.g. ``io``/``decode``/``idle``) and still making
        progress. A few assignments — cheap enough for per-stage calls."""
        if self.health_enabled:
            self.heartbeats[self._entity] = (stage, time.perf_counter(),
                                             self._items_done)

    def beat_entity(self, entity: str, stage: str, items: int = 0) -> None:
        """Publish a heartbeat for an auxiliary entity this worker owns
        (e.g. its background readahead reader thread)."""
        if self.health_enabled:
            self.heartbeats[entity] = (stage, time.perf_counter(), items)

    def item_done(self) -> None:
        """Mark one ventilated item fully processed (pools call this after
        ``process()`` returns); bumps the items counter and beats ``idle``."""
        self._items_done += 1
        self.beat('idle')

    def heartbeat_snapshot(self) -> dict:
        """``{entity: {'stage', 'ts', 'items', 'pid'}}`` for every entity
        this worker publishes. Safe to call from any thread."""
        pid = self._trace_pid
        return {entity: {'stage': stage, 'ts': ts, 'items': items, 'pid': pid}
                for entity, (stage, ts, items) in list(self.heartbeats.items())}

    def record_count(self, name: str, n: int = 1) -> None:
        """Accumulate ``n`` against a ``ReaderStats`` counter."""
        self.stat_counts[name] = self.stat_counts.get(name, 0) + n

    def record_gauge(self, name: str, value) -> None:
        """Sample a ``ReaderStats`` gauge (last value wins within one item)."""
        self.stat_gauges[name] = value

    def drain_stage_times(self) -> dict:
        """Return and reset the accumulated per-stage times."""
        times, self.stage_times = self.stage_times, {}
        return times

    def drain_stat_counts(self):
        """Return and reset ``(counters, gauges)`` accumulated since the last
        drain."""
        counts, self.stat_counts = self.stat_counts, {}
        gauges, self.stat_gauges = self.stat_gauges, {}
        return counts, gauges

    def record_latency(self, stage: str, seconds: float) -> None:
        """Record one duration observation against a latency stage (see
        :data:`petastorm_tpu.latency.STAGES`) — used by the decode sites
        whose durations only the tracer spans measured before (span
        recording is gated on tracing; tail latencies must not be). No-op
        under the kill switch."""
        if self.latency is not None:
            self.latency.record(stage, seconds)

    def drain_latency(self):
        """Return and reset the accumulated latency bucket deltas
        (``None`` when the plane is off or nothing was recorded); same drain
        discipline as :meth:`drain_stage_times`."""
        if self.latency is None:
            return None
        return self.latency.drain()

    def record_quarantine(self, record: dict) -> None:
        """Accumulate one bad-sample quarantine record (see
        :func:`petastorm_tpu.lineage.make_quarantine_record`); drained like
        the stats after each processed item."""
        self.quarantine_records.append(record)

    def drain_quarantines(self) -> list:
        """Return and reset the accumulated quarantine records."""
        records, self.quarantine_records = self.quarantine_records, []
        return records

    def record_empty_publish(self, provenance) -> None:
        """Accumulate the provenance of an item that was processed fine but
        legitimately produced ZERO results (empty drop-partition slice,
        predicate matching nothing, empty row group). No payload crosses the
        pool, so the record travels the accounting channel instead — without
        it the coverage audit would misread the item as a silent drop."""
        self.empty_publishes.append(provenance)

    def drain_empty_publishes(self) -> list:
        records, self.empty_publishes = self.empty_publishes, []
        return records

    def record_span(self, name: str, cat: str, start_s: float, dur_s: float,
                    args=None) -> None:
        """Record one trace span (``start_s`` on the ``time.perf_counter()``
        clock), stamped with this process/thread as its track. No-op unless
        the pool enabled tracing."""
        if not self.tracing_enabled:
            return
        self.trace_spans.append((name, cat, start_s, dur_s, self._trace_pid,
                                 threading.get_ident(), args))

    def drain_spans(self) -> list:
        """Return and reset the accumulated trace spans (same drain
        discipline as :meth:`drain_stage_times`)."""
        spans, self.trace_spans = self.trace_spans, []
        return spans

    def shutdown(self):
        """Optional cleanup hook invoked when the pool stops."""
