"""Worker protocol (reference ``petastorm/workers_pool/worker_base.py:18-35``)."""

from abc import ABC, abstractmethod


class WorkerBase(ABC):
    """A worker processes ventilated items and emits 0..n results via
    ``publish_func``. One instance lives per thread/process."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    @abstractmethod
    def process(self, *args, **kwargs):
        """Process one ventilated work item; call ``self.publish_func(result)``
        zero or more times."""

    def shutdown(self):
        """Optional cleanup hook invoked when the pool stops."""
