"""Single-threaded pool: work executes lazily inside ``get_results()``.

Reference parity: ``petastorm/workers_pool/dummy_pool.py:20-91``. Exists so
profilers/debuggers see worker code on the caller thread, and for fully
deterministic tests.
"""

from __future__ import annotations

import time
from collections import deque

from petastorm_tpu.workers import EmptyResultError, VentilatedItemProcessedMessage
from petastorm_tpu.workers.stats import ReaderStats, finalize_item_times


class DummyPool:
    def __init__(self, workers_count: int = 1, tracer=None, **_unused):
        self._work_queue = deque()
        self._results_queue = deque()
        self._worker = None
        self._ventilator = None
        self.stats = ReaderStats()
        #: Optional :class:`petastorm_tpu.tracing.Tracer`; spans record on
        #: the caller thread (work executes lazily inside ``get_results``).
        self.tracer = tracer
        #: Optional :class:`petastorm_tpu.lineage.LineageTracker` (set by the
        #: Reader before :meth:`start`) receiving quarantine records.
        self.lineage = None

    @property
    def workers_count(self) -> int:
        return 1

    def start(self, worker_class, worker_args=None, ventilator=None):
        self._worker = worker_class(0, self._results_queue.append, worker_args)
        self._ventilator = ventilator
        if ventilator is not None:
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        self._work_queue.append((args, kwargs))

    def get_results(self, timeout=None):
        while True:
            if self._results_queue:
                self.stats.add('items_out')
                return self._results_queue.popleft()
            if self._work_queue:
                args, kwargs = self._work_queue.popleft()
                beat = getattr(self._worker, 'beat', None)
                if beat is not None:
                    beat('processing')
                start = time.perf_counter()
                self._worker.process(*args, **kwargs)
                elapsed = time.perf_counter() - start
                item_done = getattr(self._worker, 'item_done', None)
                if item_done is not None:
                    item_done()
                times = self._worker.drain_stage_times() \
                    if hasattr(self._worker, 'drain_stage_times') else {}
                self.stats.merge_times(finalize_item_times(times, elapsed))
                if hasattr(self._worker, 'drain_stat_counts'):
                    counts, gauges = self._worker.drain_stat_counts()
                    self.stats.merge_counts(counts)
                    self.stats.merge_gauges(gauges)
                if hasattr(self._worker, 'drain_latency'):
                    self.stats.merge_latency(self._worker.drain_latency())
                if hasattr(self._worker, 'drain_quarantines'):
                    quarantines = self._worker.drain_quarantines()
                    if quarantines and self.lineage is not None:
                        self.lineage.add_quarantines(quarantines)
                if hasattr(self._worker, 'drain_empty_publishes'):
                    for prov in self._worker.drain_empty_publishes():
                        if self.lineage is not None:
                            self.lineage.register(prov)
                if self.tracer is not None:
                    self.tracer.add_span('process_item', 'worker', start,
                                         elapsed)
                    if hasattr(self._worker, 'drain_spans'):
                        self.tracer.merge(self._worker.drain_spans())
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if self._ventilator is None or self._ventilator.completed():
                raise EmptyResultError()
            # The ventilator thread has not filled the work queue yet.
            time.sleep(0.001)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()

    def heartbeats(self):
        """Live heartbeat records of the single in-process worker."""
        snapshot = getattr(self._worker, 'heartbeat_snapshot', None)
        return snapshot() if snapshot is not None else {}

    @property
    def diagnostics(self):
        out = {'output_queue_size': len(self._results_queue)}
        out.update(self.stats.snapshot())
        return out
