"""Payload serializers for the process pool transport.

Reference parity: ``petastorm/reader_impl/pickle_serializer.py:17-23`` and
``arrow_table_serializer.py:18-33`` (RecordBatch IPC stream; an empty buffer
encodes ``None``).
"""

from __future__ import annotations

import pickle

import pyarrow as pa


class PickleSerializer:
    def serialize(self, data) -> bytes:
        return pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, payload: bytes):
        return pickle.loads(payload)


class ArrowTableSerializer:
    """Zero-copy-friendly serializer for ``pa.Table`` payloads using the Arrow
    IPC stream format."""

    def serialize(self, table) -> bytes:
        if table is None:
            return b''
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            for batch in table.to_batches():
                writer.write_batch(batch)
        return sink.getvalue().to_pybytes()

    def deserialize(self, payload):
        if len(payload) == 0:
            return None
        with pa.ipc.open_stream(pa.py_buffer(payload)) as reader:
            return reader.read_all()
