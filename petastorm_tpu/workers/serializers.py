"""Payload serializers for the process pool transport.

Reference parity: ``petastorm/reader_impl/pickle_serializer.py:17-23`` and
``arrow_table_serializer.py:18-33`` (RecordBatch IPC stream; an empty buffer
encodes ``None``).

Transport contract: the process pool moves payloads as ZMQ **multipart
frames**. Every serializer implements

- ``serialize_multipart(data) -> [frame0, ...]`` — a list of buffer-protocol
  objects (bytes / memoryview / ``pa.Buffer``), and
- ``deserialize_multipart(frames) -> data`` — accepting any buffer-protocol
  objects (the pool hands back ``bytes`` with ``zmq_copy_buffers=True`` and
  zero-copy ``memoryview``s over ZMQ frame buffers with ``False``).

Single-frame serializers keep the legacy ``serialize``/``deserialize`` pair;
:class:`ZeroCopySerializer` is genuinely multi-frame (pickle protocol 5 with
out-of-band :class:`pickle.PickleBuffer`\\ s) so ndarray/Arrow payload bytes
are never copied into a pickle blob.

Each instance counts ``copies`` (full-payload memcpys it performed) and
``bytes_moved`` — the counters ``benchmark/transport.py`` and the acceptance
assertions read.
"""

from __future__ import annotations

import pickle

import pyarrow as pa

#: Buffers smaller than this stay in-band: a ZMQ frame per 100-byte array
#: would cost more in framing overhead than one memcpy saves.
_INBAND_THRESHOLD_BYTES = 64 * 1024


class PickleSerializer:
    """Monolithic-blob pickling: one full-payload memcpy on each side."""

    def __init__(self):
        self.copies = 0
        self.bytes_moved = 0

    def serialize(self, data) -> bytes:
        blob = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
        self.copies += 1
        self.bytes_moved += len(blob)
        return blob

    def deserialize(self, payload):
        self.copies += 1
        return pickle.loads(payload)

    def serialize_multipart(self, data):
        return [self.serialize(data)]

    def deserialize_multipart(self, frames):
        return self.deserialize(frames[0])


class ZeroCopySerializer:
    """Pickle protocol 5 with out-of-band buffers.

    Frame 0 is the pickle metadata stream (object structure + small scalars);
    frames 1..N are the raw payload buffers, handed to ZMQ without ever being
    copied into the pickle blob. On deserialize the buffers are passed to
    ``pickle.loads(..., buffers=...)`` and ndarrays reconstruct as views over
    the received frames — zero payload memcpys on either side. Note the
    received arrays are **read-only** when the transport hands us read-only
    frames; consumers that mutate in place must copy first.

    Fallbacks (all still correct, just not zero-copy): non-contiguous
    ndarrays and unicode/object columns pickle in-band, as do buffers under
    ``inband_threshold`` bytes (per-frame overhead would exceed the memcpy).
    """

    def __init__(self, inband_threshold: int = _INBAND_THRESHOLD_BYTES):
        self.inband_threshold = inband_threshold
        self.copies = 0
        self.bytes_moved = 0

    def serialize_multipart(self, data):
        frames = [None]  # placeholder for the metadata frame

        def keep_out_of_band(pickle_buffer):
            try:
                raw = pickle_buffer.raw()
            except BufferError:      # non-contiguous exporter: in-band copy
                self.copies += 1
                return True
            if raw.nbytes < self.inband_threshold:
                return True          # in-band (returns true => not out-of-band)
            frames.append(raw)
            self.bytes_moved += raw.nbytes
            return False

        meta = pickle.dumps(data, protocol=5, buffer_callback=keep_out_of_band)
        frames[0] = meta
        self.bytes_moved += len(meta)
        return frames

    def deserialize_multipart(self, frames):
        return pickle.loads(frames[0], buffers=list(frames[1:]))


class ArrowTableSerializer:
    """Zero-copy-friendly serializer for ``pa.Table`` payloads using the Arrow
    IPC stream format.

    ``serialize`` returns the ``pa.Buffer`` from the IPC sink directly (one
    write into the sink; no ``to_pybytes`` re-copy), and ``deserialize``
    accepts any buffer-protocol object — ``bytes``, ``memoryview`` over a ZMQ
    frame, or ``pa.Buffer`` — and reads the table zero-copy over it.
    """

    def __init__(self):
        self.copies = 0
        self.bytes_moved = 0

    def serialize(self, table):
        if table is None:
            return b''
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            for batch in table.to_batches():
                writer.write_batch(batch)
        buf = sink.getvalue()
        self.copies += 1            # the one IPC write into the sink
        self.bytes_moved += buf.size
        return buf

    def deserialize(self, payload):
        buf = payload if isinstance(payload, pa.Buffer) else pa.py_buffer(payload)
        if buf.size == 0:
            return None
        with pa.ipc.open_stream(buf) as reader:
            return reader.read_all()

    def serialize_multipart(self, table):
        return [self.serialize(table)]

    def deserialize_multipart(self, frames):
        return self.deserialize(frames[0])


def as_multipart(serializer):
    """Adapt a legacy single-frame serializer (``serialize``/``deserialize``
    only) to the multipart transport contract; passthrough otherwise."""
    if hasattr(serializer, 'serialize_multipart'):
        return serializer
    return _SingleFrameAdapter(serializer)


class _SingleFrameAdapter:
    def __init__(self, serializer):
        self._serializer = serializer
        self.copies = 0
        self.bytes_moved = 0

    def serialize_multipart(self, data):
        return [self._serializer.serialize(data)]

    def deserialize_multipart(self, frames):
        return self._serializer.deserialize(frames[0])
