"""Launch a python function in a brand-new interpreter (not a fork).

Reference parity: ``petastorm/workers_pool/exec_in_new_process.py:26-69``. The
reference avoids fork because it broke JVM-based HDFS drivers
(``process_pool.py:15-17``); we avoid it because **libtpu must only initialize
in the main process** — spawned clean interpreters are pinned to
``JAX_PLATFORMS=cpu`` so a worker can never grab the TPU (SURVEY.md §7
"hard parts").
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile


def exec_in_new_process(func, args=(), kwargs=None) -> subprocess.Popen:
    """Serialize ``(func, args, kwargs)`` with dill to a temp file and launch
    ``python -m petastorm_tpu.workers.exec_in_new_process <file>``."""
    import dill
    fd, path = tempfile.mkstemp(prefix='petastorm_tpu_bootstrap_', suffix='.dill')
    with os.fdopen(fd, 'wb') as f:
        dill.dump((func, tuple(args), dict(kwargs or {})), f)
    env = dict(os.environ)
    # Workers stay pure-CPU: the TPU runtime belongs to the main process only.
    env['JAX_PLATFORMS'] = 'cpu'
    env.setdefault('PYTHONPATH', '')
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if repo_root not in env['PYTHONPATH'].split(os.pathsep):
        env['PYTHONPATH'] = os.pathsep.join(p for p in [repo_root, env['PYTHONPATH']] if p)
    return subprocess.Popen([sys.executable, '-m', 'petastorm_tpu.workers.exec_in_new_process',
                             path], env=env)


def _main():
    import dill
    path = sys.argv[1]
    try:
        with open(path, 'rb') as f:
            func, args, kwargs = dill.load(f)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    func(*args, **kwargs)


if __name__ == '__main__':
    _main()
