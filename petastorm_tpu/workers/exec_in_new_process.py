"""Launch a python function in a brand-new interpreter (not a fork).

Reference parity: ``petastorm/workers_pool/exec_in_new_process.py:26-69``. The
reference avoids fork because it broke JVM-based HDFS drivers
(``process_pool.py:15-17``); we avoid it because **libtpu must only initialize
in the main process** — spawned clean interpreters are pinned to
``JAX_PLATFORMS=cpu`` so a worker can never grab the TPU (SURVEY.md §7
"hard parts").
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile


def exec_in_new_process(func, args=(), kwargs=None) -> subprocess.Popen:
    """Serialize ``(func, args, kwargs)`` with dill to a temp file and launch
    ``python -S -m petastorm_tpu.workers.exec_in_new_process <file>``.

    ``-S`` skips ``site``/``sitecustomize`` in the worker: environments that
    register accelerator plugins at interpreter startup (e.g. a sitecustomize
    importing jax) would otherwise pay seconds of import time per worker —
    and workers must never touch the accelerator runtime anyway. The parent's
    fully-resolved ``sys.path`` is passed via PYTHONPATH, so everything
    importable in the parent (including ``.pth``-added entries) stays
    importable in the worker. Set ``PETASTORM_TPU_WORKER_SITE=1`` to restore
    normal site initialization if a worker dependency needs it."""
    import dill
    fd, path = tempfile.mkstemp(prefix='petastorm_tpu_bootstrap_', suffix='.dill')
    with os.fdopen(fd, 'wb') as f:
        dill.dump((func, tuple(args), dict(kwargs or {})), f)
    env = dict(os.environ)
    # Workers stay pure-CPU: the TPU runtime belongs to the main process only.
    env['JAX_PLATFORMS'] = 'cpu'
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    use_site = env.get('PETASTORM_TPU_WORKER_SITE') == '1'
    interpreter = [sys.executable] if use_site else [sys.executable, '-S']
    if use_site:
        paths = [repo_root] + env.get('PYTHONPATH', '').split(os.pathsep)
    else:
        paths = [repo_root] + [p for p in sys.path if p]
    seen, deduped = set(), []
    for p in paths:
        if p and p not in seen:
            seen.add(p)
            deduped.append(p)
    env['PYTHONPATH'] = os.pathsep.join(deduped)
    return subprocess.Popen(
        interpreter + ['-m', 'petastorm_tpu.workers.exec_in_new_process', path],
        env=env)


def _main():
    import dill
    path = sys.argv[1]
    try:
        try:
            with open(path, 'rb') as f:
                func, args, kwargs = dill.load(f)
        finally:
            try:
                os.remove(path)
            except OSError:
                pass
        func(*args, **kwargs)
    except ImportError as e:
        if not sys.flags.no_site:
            raise
        # -S skips .pth execution, which PEP 660 editable installs rely on for
        # their meta-path finders; point the user at the escape hatch.
        raise ImportError(
            '{} (worker started with -S to skip site initialization; if the '
            'missing module comes from an editable install or a .pth hook, '
            'set PETASTORM_TPU_WORKER_SITE=1 to restore normal site '
            'startup)'.format(e)) from e


if __name__ == '__main__':
    _main()
