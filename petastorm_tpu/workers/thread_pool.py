"""In-process worker pool over stdlib threading.

Reference parity: ``petastorm/workers_pool/thread_pool.py`` — worker loop
(:51-75), bounded results queue (:79), stop-aware puts (:200-214), end-of-data
accounting (:145-176), exception shipping (:68-73), per-thread cProfile
(:47-49,190-198), diagnostics (:219-221).

This is the default pool: the hot decode path (pyarrow reads, numpy, cv2)
releases the GIL, so threads parallelize well without process overhead.
"""

from __future__ import annotations

import cProfile
import io
import logging
import pstats
import queue
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from petastorm_tpu.workers import (EmptyResultError, TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)
from petastorm_tpu.workers.stats import ReaderStats, finalize_item_times

logger = logging.getLogger(__name__)

_SENTINEL = object()
_RESULTS_QUEUE_SIZE_DEFAULT = 50


class _RetireSentinel:
    """A targeted shrink request on the shared work queue: whichever worker
    pops it finishes the items it already holds (the clean handback — its
    pending lookahead FIFO is processed, never dropped), then exits. The
    ``done`` event lets :meth:`ThreadPool.reap_retired` join off the hot
    path."""

    __slots__ = ('done',)

    def __init__(self):
        self.done = threading.Event()


class _WorkerException:
    """An exception captured on a worker, shipped with its formatted traceback."""

    def __init__(self, exc: BaseException):
        self.exc = exc
        self.formatted = ''.join(traceback.format_exception(type(exc), exc, exc.__traceback__))


class WorkerThread(threading.Thread):
    def __init__(self, pool: 'ThreadPool', worker, profiling_enabled: bool,
                 publish_wait: dict):
        super().__init__(daemon=True, name='petastorm-tpu-worker-{}'.format(worker.worker_id))
        self._pool = pool
        self._worker = worker
        self._publish_wait = publish_wait  # {'s': float}, fed by the publish wrapper
        self._profiler = cProfile.Profile() if profiling_enabled else None

    def run(self):
        if self._profiler:
            self._profiler.enable()
        stats = self._pool.stats
        # Readahead lookahead: a worker that exposes prefetch_lookahead > 0
        # pops up to that many EXTRA items from the shared work queue and is
        # hinted about them before processing the head — its background
        # reader overlaps the next pieces' parquet reads with the current
        # decode. The pending deque stays strictly FIFO, so single-worker
        # readers keep ventilated-piece order.
        pending = deque()
        hint = getattr(self._worker, 'prefetch_hint', None)
        beat = getattr(self._worker, 'beat', None)
        item_done = getattr(self._worker, 'item_done', None)
        retire = None
        try:
            while True:
                if retire is not None and not pending:
                    # clean retirement: every item this worker had already
                    # pulled has been processed and published — nothing was
                    # handed back by dropping (docs/autotune.md)
                    break
                if not pending:
                    item = self._pool._work_queue.get()
                    if item is _SENTINEL:
                        break
                    if isinstance(item, _RetireSentinel):
                        retire = item
                        continue
                    pending.append(item)
                lookahead = (0 if retire is not None
                             else getattr(self._worker,
                                          'prefetch_lookahead', 0))
                saw_sentinel = False
                while lookahead and len(pending) - 1 < lookahead:
                    try:
                        extra = self._pool._work_queue.get_nowait()
                    except queue.Empty:
                        break
                    if extra is _SENTINEL:
                        saw_sentinel = True
                        break
                    if isinstance(extra, _RetireSentinel):
                        # stop pulling new work; finish pending, then exit
                        retire = extra
                        break
                    pending.append(extra)
                if saw_sentinel:
                    # pool is stopping: drop un-processed lookahead items
                    # (same fate as items left on the shared queue)
                    break
                if hint is not None:
                    # hint the WHOLE pending FIFO (head included): the
                    # readahead matches outstanding prefetches as a prefix of
                    # this list, and the head's not-yet-consumed read is
                    # usually the front of that prefix
                    hint(list(pending))
                args, kwargs = pending.popleft()
                if beat is not None:
                    beat('processing')
                wait_before = self._publish_wait['s']
                published_before = self._publish_wait['items']
                start = time.perf_counter()
                try:
                    self._worker.process(*args, **kwargs)
                except (OSError, MemoryError) as e:
                    # infra failure (NEVER_QUARANTINE class). TRANSIENT
                    # storage errors that escaped the retry budget route to
                    # recovery: the worker is replaced and its items
                    # re-dispatched (docs/robustness.md). PERMANENT errors
                    # (bad path, permissions — retrying cannot help) and
                    # MemoryError (a respawned thread shares the same heap)
                    # stay LOUD: recovery converting a deleted file into a
                    # poison-item quarantine would be silent data loss.
                    from petastorm_tpu.resilience import (TRANSIENT,
                                                          classify_error)
                    if (isinstance(e, OSError)
                            and classify_error(e) == TRANSIENT
                            and self._pool._handle_worker_crash(
                                self, (args, kwargs), list(pending), e,
                                self._publish_wait['items']
                                > published_before)):
                        return
                    self._pool._put_result(_WorkerException(e))
                    raise
                except Exception as e:  # ship to consumer; keep serving
                    logger.debug('Worker %s raised:\n%s', self._worker.worker_id,
                                 traceback.format_exc())
                    self._pool._put_result(_WorkerException(e))
                except BaseException as e:
                    # a killed worker (SimulatedWorkerCrash / interpreter
                    # shutdown): recovery replaces it; when recovery is off
                    # or budget-exhausted the crash ships to the consumer —
                    # a dying thread that told nobody would turn a crash
                    # loop into a silent hang (the consumer re-raises the
                    # shipped exception; re-raising here too would only
                    # trip pytest's unhandled-thread-exception hook)
                    if self._pool._handle_worker_crash(
                            self, (args, kwargs), list(pending), e,
                            self._publish_wait['items'] > published_before):
                        return
                    self._pool._put_result(_WorkerException(e))
                    return
                elapsed = time.perf_counter() - start
                times = self._worker.drain_stage_times() \
                    if hasattr(self._worker, 'drain_stage_times') else {}
                publish_wait = self._publish_wait['s'] - wait_before
                times['worker_publish_wait_s'] = \
                    times.get('worker_publish_wait_s', 0.0) + publish_wait
                stats.merge_times(finalize_item_times(times, elapsed,
                                                      transport_s=publish_wait))
                if hasattr(self._worker, 'drain_stat_counts'):
                    counts, gauges = self._worker.drain_stat_counts()
                    stats.merge_counts(counts)
                    stats.merge_gauges(gauges)
                if hasattr(self._worker, 'drain_latency'):
                    stats.merge_latency(self._worker.drain_latency())
                if hasattr(self._worker, 'drain_quarantines'):
                    quarantines = self._worker.drain_quarantines()
                    if quarantines and self._pool.lineage is not None:
                        self._pool.lineage.add_quarantines(quarantines)
                if hasattr(self._worker, 'drain_empty_publishes'):
                    for prov in self._worker.drain_empty_publishes():
                        if self._pool.lineage is not None:
                            self._pool.lineage.register(prov)
                tracer = self._pool.tracer
                if tracer is not None:
                    tracer.add_span('process_item', 'worker', start, elapsed)
                    if hasattr(self._worker, 'drain_spans'):
                        tracer.merge(self._worker.drain_spans())
                if item_done is not None:
                    item_done()
                self._pool._put_result(VentilatedItemProcessedMessage())
        finally:
            if beat is not None:
                beat('stopped')
            if self._profiler:
                self._profiler.disable()
                self._pool._collect_profile(self._profiler)
            self._worker.shutdown()
            if retire is not None:
                self._pool._worker_retired(self, retire)


class ThreadPool:
    """Thread-based pool implementing the ventilate/get_results protocol."""

    #: The worker loop passes upcoming items to ``worker.prefetch_hint`` —
    #: readers may enable ``io_readahead`` on this pool.
    supports_prefetch_hints = True

    def __init__(self, workers_count: int, results_queue_size: int = _RESULTS_QUEUE_SIZE_DEFAULT,
                 profiling_enabled: bool = False, tracer=None, recovery=None):
        #: Worker auto-recovery options (``resilience.resolve_recovery``
        #: shape) or ``None``: with recovery on, a worker thread killed by
        #: an infra error or an injected crash is replaced and the items it
        #: held are re-dispatched exactly once (docs/robustness.md).
        self._recovery = recovery
        self._respawns_used = 0
        self._crash_counts = {}
        self._workers_count = workers_count
        self._work_queue: queue.Queue = queue.Queue()
        self._results_queue: queue.Queue = queue.Queue(maxsize=results_queue_size)
        self._profiling_enabled = profiling_enabled
        #: Optional :class:`petastorm_tpu.tracing.Tracer`; worker threads
        #: record process/io/decode spans into it directly.
        self.tracer = tracer
        #: Optional :class:`petastorm_tpu.lineage.LineageTracker` (set by the
        #: Reader before :meth:`start`); worker quarantine records drain
        #: straight into it.
        self.lineage = None
        self._profiles = []
        self._profiles_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._threads = []
        self._workers = []
        # membership lock for the thread/worker lists: resize (controller
        # thread) mutates them while stop()/heartbeats() (other threads)
        # iterate. Bodies under it are pure list/dict work — never a queue
        # op or a join (petalint R3).
        self._membership_lock = threading.Lock()
        # serializes resize against stop(): a grow that raced shutdown
        # would spawn a worker no stop sentinel ever covers (sentinel
        # counting and spawning must see a consistent stop flag); queue
        # puts happen outside it
        self._resize_mutex = threading.Lock()
        self._retired_threads = []
        self._pending_retires = []
        self._next_worker_id = workers_count
        self._start_args = None
        self._readahead_depth_override = None
        self._ventilator = None
        self._accounting_lock = threading.Lock()
        self._ventilated_items = 0
        self._processed_items = 0
        self.stats = ReaderStats()

    @property
    def workers_count(self) -> int:
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        self._ventilator = ventilator
        self._start_args = (worker_class, worker_args)
        for worker_id in range(self._workers_count):
            self._spawn_worker(worker_id)
        if ventilator is not None:
            ventilator.start()

    def _spawn_worker(self, worker_id: int) -> None:
        worker_class, worker_args = self._start_args
        if self._readahead_depth_override is not None \
                and isinstance(worker_args, dict):
            # a grow after a live set_readahead_depth must not resurrect
            # the construction-time depth: bake the current one into the
            # newcomer's args (the broadcast path only reaches workers
            # that already exist)
            worker_args = dict(worker_args,
                               io_readahead=self._readahead_depth_override)
        # Per-worker publish wrapper: time spent blocked on a full results
        # queue is back-pressure, not decode; the worker thread subtracts
        # it from its process() wall time. The worker is constructed with
        # the wrapper, so its beat fn arrives via the holder afterwards.
        # 'items' counts publications per worker: the crash handler uses it
        # to decide whether a dying worker's current item already delivered
        # its payload (then only the accounting is synthesized — never a
        # redispatch, which would be a duplicate)
        publish_wait = {'s': 0.0, 'items': 0}
        holder = {}

        def publish(item, _wait=publish_wait, _holder=holder):
            start = time.perf_counter()
            self._put_result(item, beat=_holder.get('beat'))
            _wait['s'] += time.perf_counter() - start
            _wait['items'] += 1

        worker = worker_class(worker_id, publish, worker_args)
        holder['beat'] = getattr(worker, 'beat', None)
        thread = WorkerThread(self, worker, self._profiling_enabled,
                              publish_wait)
        with self._membership_lock:
            self._workers.append(worker)
            self._threads.append(thread)
        thread.start()

    # -- live resize (the autotune controller's actuator; docs/autotune.md) ----

    def resize(self, workers_count: int, timeout_s: float = 30.0) -> int:
        """Live-resize the pool to ``workers_count`` workers.

        Growing spawns named worker threads immediately. Shrinking enqueues
        retire sentinels on the shared work queue: whichever workers pop
        them finish every item they already hold (a clean handback — the
        lineage audit sees each of those items delivered exactly once, never
        dropped), publish their final drained stats, run ``shutdown()`` and
        exit. Retired threads are joined off the hot path — here, bounded by
        ``timeout_s``, and again by :meth:`join`. Returns the new target
        count."""
        if not isinstance(workers_count, int) or workers_count < 1:
            raise ValueError('workers_count must be a positive int, got '
                             '{!r}'.format(workers_count))
        sentinels = []
        with self._resize_mutex:
            if self._stop_event.is_set():
                return self._workers_count
            delta = workers_count - self._workers_count
            if delta > 0:
                for _ in range(delta):
                    worker_id = self._next_worker_id
                    self._next_worker_id += 1
                    self._spawn_worker(worker_id)
            elif delta < 0:
                sentinels = [_RetireSentinel() for _ in range(-delta)]
                with self._membership_lock:
                    self._pending_retires.extend(sentinels)
            self._workers_count = workers_count
        for sentinel in sentinels:
            self._work_queue.put(sentinel)
        if sentinels:
            self.reap_retired(timeout_s)
        return self._workers_count

    def _worker_retired(self, thread: 'WorkerThread', sentinel) -> None:
        """Called by a retiring worker thread as its last act: move it to
        the retired list (``reap_retired``/``join`` own the joining — a
        thread never joins itself)."""
        with self._membership_lock:
            if thread in self._threads:
                self._threads.remove(thread)
            if thread._worker in self._workers:
                self._workers.remove(thread._worker)
            if sentinel in self._pending_retires:
                self._pending_retires.remove(sentinel)
            self._retired_threads.append(thread)
        sentinel.done.set()

    def reap_retired(self, timeout_s: float = 10.0) -> int:
        """Join retired worker threads (bounded); returns how many are still
        pending retirement (0 = fully settled). Safe from any thread except
        a worker's own."""
        deadline = time.monotonic() + timeout_s
        with self._membership_lock:
            pending = list(self._pending_retires)
        for sentinel in pending:
            if self._stop_event.is_set():
                # a stopping pool's workers exit via _SENTINEL and may never
                # consume a pending retire — don't wait out the timeout on
                # a sentinel that cannot complete
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            sentinel.done.wait(remaining)
        with self._membership_lock:
            retired, self._retired_threads = self._retired_threads, []
            still_pending = len(self._pending_retires)
        for thread in retired:
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
        return still_pending

    # -- worker auto-recovery (docs/robustness.md) -----------------------------

    @staticmethod
    def _item_key(item):
        """Stable identity of a work item across epochs/redispatches (poison
        accounting): reader items carry ``piece_index``/partition kwargs;
        anything else keys by its repr."""
        args, kwargs = item
        piece_index = kwargs.get('piece_index') \
            if isinstance(kwargs, dict) else None
        if piece_index is None:
            return ('raw', repr((args, kwargs))[:200])
        return (piece_index,
                tuple(kwargs.get('shuffle_row_drop_partition') or (0, 1)))

    def _quarantine_poison(self, item, crash_count: int) -> None:
        from petastorm_tpu.lineage import crash_quarantine_record
        _args, kwargs = item
        piece_index = kwargs.get('piece_index') \
            if isinstance(kwargs, dict) else None
        tracker = self.lineage
        logger.error('poison item %s killed %d worker(s); quarantining it '
                     'instead of crash-looping', self._item_key(item),
                     crash_count)
        if tracker is not None and tracker.enabled and piece_index is not None:
            tracker.add_quarantines([crash_quarantine_record(
                tracker, piece_index, kwargs.get('epoch', 0),
                kwargs.get('shuffle_row_drop_partition', (0, 1)),
                crash_count)])

    def _handle_worker_crash(self, thread, current_item, pending_items,
                             exc, published: bool) -> bool:
        """A worker thread is dying mid-item. With recovery on (and budget
        left): replace it, hand its items back, and return True — the dying
        thread exits quietly. Exactly-once: a current item that already
        published its payload is never re-dispatched (only its missing
        accounting message is synthesized); un-published items go back on
        the shared work queue, unless they crossed the poison threshold —
        then they are quarantined through the lineage channel instead of
        crash-looping the pool. Returns False when recovery is off,
        budget-exhausted, or the pool is stopping (caller keeps the
        pre-recovery behavior)."""
        recovery = self._recovery
        if recovery is None or self._stop_event.is_set():
            return False
        budget = recovery.get('max_respawns')
        if budget is None:
            budget = max(3, self._workers_count)
        with self._resize_mutex:
            if self._stop_event.is_set() or self._respawns_used >= budget:
                if self._respawns_used >= budget:
                    logger.error('worker respawn budget exhausted (%d); '
                                 'letting the crash surface', budget)
                return False
            self._respawns_used += 1
            with self._membership_lock:
                if thread in self._threads:
                    self._threads.remove(thread)
                if thread._worker in self._workers:
                    self._workers.remove(thread._worker)
            worker_id = self._next_worker_id
            self._next_worker_id += 1
            self._spawn_worker(worker_id)
        logger.warning('worker thread %s died (%s: %s); respawned as '
                       'worker %d and re-dispatching %d item(s)',
                       thread.name, type(exc).__name__, exc, worker_id,
                       (0 if published else 1) + len(pending_items))
        self.stats.add('worker_respawns')
        poison_threshold = recovery.get('poison_threshold', 3)
        # unlike the process pool (which cannot see inside a dead
        # interpreter), the dying thread knows EXACTLY which item it was
        # processing: only that item accumulates a crash count — innocents
        # merely prefetched into the pending FIFO carry no suspicion — and
        # it requeues LAST so the innocents complete before it can crash
        # the replacement
        redispatched = 0
        for item in pending_items:
            self._work_queue.put(item)
            redispatched += 1
        if published:
            # payload already in the results queue: the item WAS delivered;
            # synthesize only the accounting the dying worker never sent
            self._put_result(VentilatedItemProcessedMessage())
        else:
            key = self._item_key(current_item)
            count = self._crash_counts.get(key, 0) + 1
            self._crash_counts[key] = count
            if count >= poison_threshold:
                self._quarantine_poison(current_item, count)
                self.stats.add('poison_items_quarantined')
                self._put_result(VentilatedItemProcessedMessage())
            else:
                self._work_queue.put(current_item)
                redispatched += 1
        if redispatched:
            self.stats.add('items_redispatched', redispatched)
        return True

    def set_readahead_depth(self, depth: int) -> None:
        """Live-set every worker's readahead prefetch depth (no-op for
        workers without the readahead machinery); workers spawned by a
        later grow inherit it."""
        self._readahead_depth_override = depth
        with self._membership_lock:
            workers = list(self._workers)
        for worker in workers:
            setter = getattr(worker, 'set_readahead_depth', None)
            if setter is not None:
                setter(depth)

    def set_results_queue_bound(self, maxsize: int) -> None:
        """Live-adjust the bounded results queue's capacity. Relies on
        CPython's ``queue.Queue`` keeping ``maxsize`` as a plain attribute
        guarded by ``mutex``; blocked putters are woken so an enlargement
        takes effect immediately rather than at the next consumer get."""
        if not isinstance(maxsize, int) or maxsize < 1:
            raise ValueError('results queue bound must be a positive int, '
                             'got {!r}'.format(maxsize))
        q = self._results_queue
        with q.mutex:
            q.maxsize = maxsize
            q.not_full.notify_all()

    @property
    def results_queue_bound(self) -> int:
        return self._results_queue.maxsize

    def ventilate(self, *args, **kwargs):
        with self._accounting_lock:
            self._ventilated_items += 1
        self._work_queue.put((args, kwargs))

    def _put_result(self, item, beat=None):
        """Bounded put that gives up when the pool is stopping
        (reference ``_stop_aware_put``, ``thread_pool.py:200-214``).

        ``beat`` (the publishing worker's heartbeat fn) marks time blocked
        on a full queue as idle-class ``backpressured``: a paused consumer
        (checkpoint save, eval) must not read as a stalled worker — the
        same exemption the ventilator's ``_acquire_slot`` applies."""
        blocked = False
        while not self._stop_event.is_set():
            try:
                self._results_queue.put(item, timeout=0.05)
                if blocked and beat is not None:
                    beat('processing')
                return
            except queue.Full:
                if not blocked and beat is not None:
                    blocked = True
                    beat('backpressured')
                continue

    def _all_work_consumed(self) -> bool:
        with self._accounting_lock:
            counts_settled = self._ventilated_items == self._processed_items
        if not counts_settled:
            return False
        if self._ventilator is not None:
            return self._ventilator.completed()
        return True

    def get_results(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        entered = time.perf_counter()
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutWaitingForResultError(
                    'No results after {:.1f}s'.format(timeout))
            try:
                wait_start = time.perf_counter()
                item = self._results_queue.get(timeout=0.1)
                self.stats.add_time('queue_wait_s',
                                    time.perf_counter() - wait_start)
            except queue.Empty:
                self.stats.add_time('queue_wait_s',
                                    time.perf_counter() - wait_start)
                if self._all_work_consumed() and self._results_queue.empty():
                    raise EmptyResultError()
                continue
            if isinstance(item, VentilatedItemProcessedMessage):
                with self._accounting_lock:
                    self._processed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                # Eager end-of-data check: the final accounting message is the
                # moment the stream ends — detecting it here instead of on the
                # next get() timeout saves a flat 100ms per epoch boundary
                # (measurable: ~40% of a small-dataset epoch's wall time).
                if self._all_work_consumed() and self._results_queue.empty():
                    raise EmptyResultError()
                continue
            if isinstance(item, _WorkerException):
                self.stop()
                sys.stderr.write(item.formatted)
                raise item.exc
            self.stats.gauge('queue_depth', self._results_queue.qsize())
            self.stats.add('items_out')
            now = time.perf_counter()
            # full consumer wait for THIS delivery (the same interval the
            # queue_wait span covers) — one histogram observation per item,
            # not the 100ms-clamped poll slices queue_wait_s accumulates
            self.stats.record_latency('queue_wait', now - entered)
            if self.tracer is not None:
                self.tracer.add_span('queue_wait', 'consumer', entered,
                                     now - entered)
            return item

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        # the resize mutex makes the stop flag + live-thread count atomic
        # against a concurrent grow: a worker spawned before this point is
        # counted (gets a sentinel), one after sees the flag and never
        # spawns
        with self._resize_mutex:
            self._stop_event.set()
            with self._membership_lock:
                live_threads = len(self._threads)
        for _ in range(live_threads):
            self._work_queue.put(_SENTINEL)

    def join(self):
        with self._membership_lock:
            threads = list(self._threads) + list(self._retired_threads)
        for thread in threads:
            thread.join(timeout=10)
        if self._profiling_enabled and self._profiles:
            stats = None
            for p in self._profiles:
                if stats is None:
                    stats = pstats.Stats(p)
                else:
                    stats.add(p)
            out = io.StringIO()
            stats.stream = out
            stats.sort_stats('cumulative').print_stats(30)
            logger.info('Aggregated worker profile:\n%s', out.getvalue())

    def _collect_profile(self, profiler):
        with self._profiles_lock:
            self._profiles.append(profiler)

    def heartbeats(self):
        """Live per-entity heartbeat records (workers run in-process, so
        their ``WorkerBase`` records are read directly — never stale)."""
        records = {}
        with self._membership_lock:
            workers = list(self._workers)
        for worker in workers:
            snapshot = getattr(worker, 'heartbeat_snapshot', None)
            if snapshot is not None:
                records.update(snapshot())
        return records

    @property
    def diagnostics(self):
        out = {'output_queue_size': self._results_queue.qsize()}
        out.update(self.stats.snapshot())
        return out
