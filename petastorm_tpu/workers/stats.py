"""Per-stage pipeline telemetry shared by every worker pool.

One :class:`ReaderStats` instance lives on each pool (``pool.stats``) and is
surfaced through ``Reader.diagnostics``. Stages cover the whole path a sample
travels: parquet read (``worker_io_s``), codec decode (``worker_decode_s``),
transport serialize/deserialize (process pools), result-queue wait on the
consumer side, and device staging (``jax_utils`` records into the same
instance). Counters track payload bytes moved, full-payload memcpys
(``payload_copies`` — the number the zero-copy transport exists to drive to
zero), and items delivered; gauges sample queue/buffer occupancy.

Process workers live in other interpreters: they accumulate per-item stage
times locally and ship them back inside the
:class:`~petastorm_tpu.workers.VentilatedItemProcessedMessage` control frame,
which the pool merges here via :meth:`merge_times`.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

#: Wall-time stages, in pipeline order. All are seconds.
TIME_STAGES = (
    'worker_io_s',       # parquet row-group read inside the worker
    'worker_decode_s',   # codec decode / transform inside the worker
    'worker_publish_wait_s',  # worker blocked on a full results queue
    'serialize_s',       # payload -> transport frames (process pools)
    'deserialize_s',     # transport frames -> payload (consumer side)
    'queue_wait_s',      # consumer blocked waiting for a result
    'device_stage_s',    # host -> device transfer (jax loaders)
)

#: Monotonic counters.
COUNTERS = (
    'bytes_moved',       # payload bytes that crossed the worker->consumer hop
    'payload_copies',    # full-payload memcpys made by the transport
    'payload_frames',    # transport frames shipped (multipart parts)
    'items_out',         # results delivered to the consumer
)

#: Occupancy gauges; each also keeps a ``<name>_max`` high-water mark.
GAUGES = ('queue_depth', 'shuffle_buffer_depth')


class ReaderStats:
    """Thread-safe per-stage accumulator. All keys exist from construction so
    ``snapshot()`` has a stable schema regardless of pool type."""

    __slots__ = ('_lock', '_times', '_counts', '_gauges')

    def __init__(self):
        self._lock = threading.Lock()
        self._times = {stage: 0.0 for stage in TIME_STAGES}
        self._counts = {name: 0 for name in COUNTERS}
        self._gauges = {}
        for name in GAUGES:
            self._gauges[name] = 0
            self._gauges[name + '_max'] = 0

    def add_time(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._times[stage] = self._times.get(stage, 0.0) + seconds

    def merge_times(self, stage_seconds) -> None:
        """Accumulate a ``{stage: seconds}`` mapping (shipped back from a
        process worker)."""
        if not stage_seconds:
            return
        with self._lock:
            for stage, seconds in stage_seconds.items():
                self._times[stage] = self._times.get(stage, 0.0) + seconds

    def add(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counts[counter] = self._counts.get(counter, 0) + n

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value
            key = name + '_max'
            if value > self._gauges.get(key, 0):
                self._gauges[key] = value

    @contextmanager
    def timed(self, stage: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add_time(stage, time.perf_counter() - start)

    def snapshot(self) -> dict:
        """One flat dict of every stage/counter/gauge (stable key set)."""
        with self._lock:
            out = dict(self._times)
            out.update(self._counts)
            out.update(self._gauges)
        return out


def finalize_item_times(times: dict, elapsed: float,
                        transport_s: float = 0.0) -> dict:
    """Derive ``worker_decode_s`` for one processed item so the stages sum
    sanely: decode = total ``process()`` wall time minus transport time
    (serialize + publish wait) minus the already-itemized io read time.
    Mutates and returns ``times`` (the worker's drained stage dict). The one
    definition shared by the thread/process/dummy pools."""
    times['worker_decode_s'] = times.get('worker_decode_s', 0.0) \
        + max(0.0, elapsed - transport_s - times.get('worker_io_s', 0.0))
    return times


def stage_keys() -> tuple:
    """The stable key set of :meth:`ReaderStats.snapshot` (tests assert it)."""
    keys = list(TIME_STAGES) + list(COUNTERS)
    for name in GAUGES:
        keys.extend((name, name + '_max'))
    return tuple(keys)
