"""Per-stage pipeline telemetry shared by every worker pool.

One :class:`ReaderStats` instance lives on each pool (``pool.stats``) and is
surfaced through ``Reader.diagnostics``. Stages cover the whole path a sample
travels: parquet read (``worker_io_s``), codec decode (``worker_decode_s``),
transport serialize/deserialize (process pools), result-queue wait on the
consumer side, and device staging (``jax_utils`` records into the same
instance). Counters track payload bytes moved, full-payload memcpys
(``payload_copies`` — the number the zero-copy transport exists to drive to
zero), and items delivered; gauges sample queue/buffer occupancy.

Process workers live in other interpreters: they accumulate per-item stage
times locally and ship them back inside the
:class:`~petastorm_tpu.workers.VentilatedItemProcessedMessage` control frame,
which the pool merges here via :meth:`merge_times`.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from petastorm_tpu.latency import PipelineLatency, latency_enabled

#: Wall-time stages, in pipeline order. All are seconds.
TIME_STAGES = (
    'worker_io_s',       # storage stall inside the worker (inline reads +
                         # time blocked waiting on an unfinished prefetch)
    'readahead_io_s',    # parquet reads issued by the background readahead
                         # thread (overlaps worker_decode_s by construction)
    'readahead_wait_s',  # worker blocked on a prefetched-but-unfinished read
                         # (the un-hidden part of readahead_io_s; also
                         # counted in worker_io_s)
    'worker_decode_s',   # codec decode / transform inside the worker
    'worker_publish_wait_s',  # worker blocked on a full results queue
    'serialize_s',       # payload -> transport frames (process pools)
    'deserialize_s',     # transport frames -> payload (consumer side)
    'queue_wait_s',      # consumer blocked waiting for a result
    'device_stage_s',    # host -> device transfer (jax loaders)
    # goodput plane (docs/goodput.md): per-training-step decomposition summed
    # by the loader's GoodputMonitor. Additive seconds — pod aggregation sums
    # them and re-derives the fractions, never averages fractions.
    'goodput_total_s',   # consumer step wall (infeed wait + train wall)
    'goodput_stall_s',   # pure data stall (fetch wait not covered by h2d)
    'goodput_h2d_s',     # h2d staging seconds on the step's critical path
    'goodput_device_s',  # device compute (fence wait; whole train wall when
                         # unfenced)
    'goodput_host_s',    # host-side overhead inside the train wall (fenced
                         # steps only)
)

#: Monotonic counters.
COUNTERS = (
    'bytes_moved',       # payload bytes that crossed the worker->consumer hop
    'payload_copies',    # full-payload memcpys made by the transport
    'payload_frames',    # transport frames shipped (multipart parts)
    'items_out',         # results delivered to the consumer
    'readahead_hits',    # row-group reads served from the prefetch queue
    'readahead_misses',  # row-group reads that went inline (not prefetched)
    'rows_quarantined',  # rows dropped under on_decode_error='skip'/'quarantine'
    'items_quarantined',  # quarantine/skip events (items or row batches)
    'rows_decoded_batched',  # codec column cells decoded by the vectorized
                             # row-group path (docs/decode.md)
    'rows_decoded_percell',  # codec column cells that fell back to the
                             # per-cell loop (wildcard shapes, nulls,
                             # decode hints, punted/corrupt chunks)
    'rows_decoded_device',   # codec column cells decoded on-device under
                             # jax.jit from bytes-through raw payloads
                             # (ops/decode.py, docs/decode.md)
    'bytes_shipped_raw',     # raw (undecoded) payload bytes workers shipped
                             # for device-planned columns instead of
                             # host-decoding them
    'shared_hits',       # row groups served from the host-wide shared cache
    'shared_misses',     # shared-cache lookups that fell through to io+decode
    'shared_evictions',  # shared-cache segments evicted/spilled (this reader)
    'shared_put_failures',  # cache segment publications that failed
                            # (ENOSPC/serialization) and degraded to direct
                            # decode — a named degradation cause in /healthz
    'io_retries',        # row-group/prefetch reads re-attempted after a
                         # transient storage error (docs/robustness.md)
    'io_hedges',         # duplicate reads fired when the primary exceeded
                         # the live hedge threshold
    'io_hedge_wins',     # hedged reads where the DUPLICATE finished first
    'io_hedge_losses',   # hedged reads where the primary still won
    'io_permanent_failures',  # reads that failed with a non-retryable
                              # (request-shaped) error
    'worker_respawns',   # crashed workers replaced by the pool supervisor
    'items_redispatched',  # in-flight items re-ventilated after a worker
                           # crash (exactly-once: deficit-checked first)
    'poison_items_quarantined',  # items quarantined after killing workers
                                 # repeatedly (no crash loop)
    'peer_skipped_dead',  # peer-cache fetches skipped because the peer was
                          # inside its dead-peer cooldown window
    'hosts_joined',      # pod members admitted by the elasticity plane
                         # (podelastic; docs/robustness.md)
    'hosts_died',        # pod members declared dead (heartbeat expiry) —
                         # a named degradation cause in /healthz
    'leases_rebalanced',  # shard leases that moved to a different host
                          # after a membership change
    'rows_resumed',      # rows a takeover host resumed from a dead host's
                         # checkpointed lease cursor (never re-delivered)
)

#: Occupancy gauges; each also keeps a ``<name>_max`` high-water mark.
#: ``shared_cache_bytes`` samples the host-wide tiered cache's approximate
#: resident bytes (tier 0 + tier 1) as seen by this reader's workers.
#: ``prefetch_occupancy`` samples the device-prefetch ring's buffered-batch
#: count at every enqueue/dequeue — an empty ring at step boundaries is the
#: classic starving signal (docs/goodput.md).
GAUGES = ('queue_depth', 'shuffle_buffer_depth', 'readahead_depth',
          'shared_cache_bytes', 'prefetch_occupancy')

#: Derived keys added to every snapshot (not accumulated directly).
#: ``items_per_s``/``mb_per_s`` are rates over the snapshot window — the time
#: since construction or the last :meth:`ReaderStats.reset` — so benchmarks
#: that ``reset()`` after warmup read steady-state rates, and the metrics
#: emitter / throughput CLI stop recomputing them ad hoc. The ``*_p50_s`` /
#: ``*_p99_s`` keys are tail-latency estimates from the streaming histograms
#: (``docs/latency.md``); 0.0 when the latency plane is disabled or has no
#: observations yet.
DERIVED = ('io_overlap_fraction', 'window_s', 'items_per_s', 'mb_per_s',
           'queue_wait_p50_s', 'queue_wait_p99_s', 'e2e_latency_p99_s',
           'io_range_p99_s', 'peer_fetch_p99_s')

#: Conditionally-derived goodput keys (docs/goodput.md): present only once
#: the goodput plane has closed at least one step (``goodput_total_s > 0``)
#: — a snapshot must never read "0% goodput" for a pipeline that simply has
#: no training loop attached. Fractions are re-derived from the summed
#: seconds at every snapshot, never accumulated.
GOODPUT_DERIVED = ('goodput_fraction', 'data_stall_fraction')

#: Snapshot key carrying the raw per-stage histogram states (bucket-count
#: pairs + sum/count) when the latency plane is on — what ``/metrics``
#: renders as Prometheus histograms and flight records embed. Absent under
#: the ``PETASTORM_TPU_LATENCY=0`` kill switch.
LATENCY_HISTOGRAMS_KEY = '_latency_histograms'

_MB = 1024.0 * 1024.0


class ReaderStats:
    """Thread-safe per-stage accumulator. All keys exist from construction so
    ``snapshot()`` has a stable schema regardless of pool type."""

    __slots__ = ('_lock', '_times', '_counts', '_gauges', '_window_start',
                 'latency')

    def __init__(self):
        self._lock = threading.Lock()
        #: The per-stage tail-latency plane (:class:`PipelineLatency`), or
        #: ``None`` under the ``PETASTORM_TPU_LATENCY=0`` kill switch — every
        #: feed site is a single attribute test. Fed from the same timing
        #: sites as the stage sums (see ``docs/latency.md``).
        self.latency = PipelineLatency() if latency_enabled() else None
        self._init_locked()

    def _init_locked(self):
        self._times = {stage: 0.0 for stage in TIME_STAGES}
        self._counts = {name: 0 for name in COUNTERS}
        self._gauges = {}
        for name in GAUGES:
            self._gauges[name] = 0
            self._gauges[name + '_max'] = 0
        self._window_start = time.perf_counter()

    def reset(self) -> None:
        """Zero every stage/counter/gauge and restart the snapshot window.
        Benchmarks call this after warmup so the measured window excludes
        warmup decode/io (and the derived rates cover only what was
        measured)."""
        with self._lock:
            self._init_locked()
        if self.latency is not None:
            self.latency.reset()

    def record_latency(self, stage: str, seconds: float) -> None:
        """Record one per-observation duration against a latency stage
        (:data:`petastorm_tpu.latency.STAGES`); no-op when the latency plane
        is disabled."""
        latency = self.latency
        if latency is not None:
            latency.record(stage, seconds)

    def merge_latency(self, deltas) -> None:
        """Absorb a worker's drained ``{stage: bucket-delta}`` mapping
        (shipped back in the accounting control message, exactly like
        :meth:`merge_counts` — a dead worker loses only unshipped deltas)."""
        latency = self.latency
        if latency is not None and deltas:
            latency.merge_deltas(deltas)

    def add_time(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._times[stage] = self._times.get(stage, 0.0) + seconds

    def merge_times(self, stage_seconds) -> None:
        """Accumulate a ``{stage: seconds}`` mapping (shipped back from a
        process worker)."""
        if not stage_seconds:
            return
        with self._lock:
            for stage, seconds in stage_seconds.items():
                self._times[stage] = self._times.get(stage, 0.0) + seconds

    def add(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counts[counter] = self._counts.get(counter, 0) + n

    def merge_counts(self, counters) -> None:
        """Accumulate a ``{counter: n}`` mapping (shipped back from a process
        worker)."""
        if not counters:
            return
        with self._lock:
            for name, n in counters.items():
                self._counts[name] = self._counts.get(name, 0) + n

    def merge_gauges(self, gauges) -> None:
        """Apply a ``{gauge: value}`` mapping of fresh samples."""
        if not gauges:
            return
        for name, value in gauges.items():
            self.gauge(name, value)

    def gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value
            key = name + '_max'
            if value > self._gauges.get(key, 0):
                self._gauges[key] = value

    @contextmanager
    def timed(self, stage: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add_time(stage, time.perf_counter() - start)

    def snapshot(self) -> dict:
        """One flat dict of every stage/counter/gauge (stable key set), plus
        the derived keys: ``io_overlap_fraction`` (share of readahead read
        time hidden behind decode, ``1 - readahead_wait_s / readahead_io_s``;
        0.0 when readahead is off), ``window_s`` (seconds since construction
        or the last :meth:`reset`), and the window rates ``items_per_s`` /
        ``mb_per_s`` (items and payload MB delivered per window second;
        ``mb_per_s`` is 0 for in-process pools, which move no transport
        bytes)."""
        with self._lock:
            out = dict(self._times)
            out.update(self._counts)
            out.update(self._gauges)
            window = time.perf_counter() - self._window_start
        ra_io = out.get('readahead_io_s', 0.0)
        ra_wait = out.get('readahead_wait_s', 0.0)
        out['io_overlap_fraction'] = (
            max(0.0, 1.0 - ra_wait / ra_io) if ra_io > 0 else 0.0)
        out['window_s'] = window
        out['items_per_s'] = out['items_out'] / window if window > 0 else 0.0
        out['mb_per_s'] = (out['bytes_moved'] / _MB / window
                           if window > 0 else 0.0)
        # tail-latency derived keys (computed outside the stats lock: the
        # histograms carry their own locks and are never nested under it)
        latency = self.latency
        if latency is not None:
            queue_wait = latency.histograms['queue_wait']
            e2e = latency.histograms['e2e_batch']
            out['queue_wait_p50_s'] = queue_wait.quantile(0.5) or 0.0
            out['queue_wait_p99_s'] = queue_wait.quantile(0.99) or 0.0
            out['e2e_latency_p99_s'] = e2e.quantile(0.99) or 0.0
            # read-plane tails (docs/pod_observability.md): lets the health
            # verdict NAME a slow object store / slow peer cache
            out['io_range_p99_s'] = (
                latency.histograms['io_range'].quantile(0.99) or 0.0)
            out['peer_fetch_p99_s'] = (
                latency.histograms['peer_fetch'].quantile(0.99) or 0.0)
            state = latency.export_state()
            if state:   # stages with observations only; never an empty key
                out[LATENCY_HISTOGRAMS_KEY] = state
        else:
            out['queue_wait_p50_s'] = 0.0
            out['queue_wait_p99_s'] = 0.0
            out['e2e_latency_p99_s'] = 0.0
            out['io_range_p99_s'] = 0.0
            out['peer_fetch_p99_s'] = 0.0
        # goodput fractions: only once a training step closed — no loader
        # (or the PETASTORM_TPU_GOODPUT=0 kill switch) means no keys at all
        fraction = goodput_fraction(out)
        if fraction is not None:
            out['goodput_fraction'] = fraction
            out['data_stall_fraction'] = data_stall_fraction(out)
        return out


def finalize_item_times(times: dict, elapsed: float,
                        transport_s: float = 0.0) -> dict:
    """Derive ``worker_decode_s`` for one processed item so the stages sum
    sanely: decode = total ``process()`` wall time minus transport time
    (serialize + publish wait) minus the already-itemized io read time.
    Mutates and returns ``times`` (the worker's drained stage dict). The one
    definition shared by the thread/process/dummy pools."""
    times['worker_decode_s'] = times.get('worker_decode_s', 0.0) \
        + max(0.0, elapsed - transport_s - times.get('worker_io_s', 0.0))
    return times


def stage_keys() -> tuple:
    """The stable key set of :meth:`ReaderStats.snapshot` (tests assert it)."""
    keys = list(TIME_STAGES) + list(COUNTERS)
    for name in GAUGES:
        keys.extend((name, name + '_max'))
    keys.extend(DERIVED)
    return tuple(keys)


def effective_io_s(snapshot: dict) -> float:
    """Total storage-read seconds in a snapshot: inline stall plus background
    readahead reads, minus the blocked wait that is counted in both
    ``worker_io_s`` and ``readahead_io_s``. The one definition every io:decode
    consumer (``recommend_io_readahead``, ``jax_utils.infeed_diagnosis``)
    shares."""
    return (snapshot.get('worker_io_s', 0.0)
            + snapshot.get('readahead_io_s', 0.0)
            - snapshot.get('readahead_wait_s', 0.0))


def progress_marker(snapshot: dict) -> tuple:
    """``(items_out, bytes_moved)`` of a snapshot — the monotone pair the
    :class:`~petastorm_tpu.health.PipelineWatchdog` compares across ticks to
    report whether the pipeline made any global progress between
    evaluations (``items_out_delta`` in its verdict)."""
    return (snapshot.get('items_out', 0), snapshot.get('bytes_moved', 0))


def readahead_hit_rate(snapshot: dict) -> float:
    """Fraction of row-group reads served from the prefetch queue."""
    hits = snapshot.get('readahead_hits', 0)
    return hits / max(1, hits + snapshot.get('readahead_misses', 0))


def batched_decode_fraction(snapshot: dict):
    """Fraction of codec column cells decoded by the vectorized row-group
    path (``None`` when no codec cells were decoded at all — scalar-only
    views must not read as "0% batched"). A decode-bound pipeline showing
    a low fraction here is paying per-cell Python the batched path exists
    to remove — ``docs/troubleshooting.md`` has the triage."""
    batched = snapshot.get('rows_decoded_batched', 0)
    percell = snapshot.get('rows_decoded_percell', 0)
    total = batched + percell
    if not total:
        return None
    return round(batched / total, 4)


def device_decode_fraction(snapshot: dict):
    """Fraction of codec column cells decoded on-device under ``jax.jit``
    (``None`` when no codec cells were decoded anywhere — same contract as
    :func:`batched_decode_fraction`). A bytes-through epoch on an all-
    eligible view reads ≈1.0; anything lower means columns declined to the
    host matrix (``docs/decode.md`` has the eligibility table) or raw
    chunks failed validation and were host-decoded + repacked."""
    device = snapshot.get('rows_decoded_device', 0)
    host = (snapshot.get('rows_decoded_batched', 0)
            + snapshot.get('rows_decoded_percell', 0))
    total = device + host
    if not total:
        return None
    return round(device / total, 4)


def goodput_fraction(snapshot: dict):
    """Fraction of consumer step wall time spent in device compute
    (``goodput_device_s / goodput_total_s``; ``None`` before any training
    step closed — an idle reader must not read as 0% goodput). Re-derived
    from the summed seconds so pod aggregation (which sums the seconds
    across hosts) yields the true pod fraction, not an average of per-host
    fractions. See ``docs/goodput.md``."""
    total = snapshot.get('goodput_total_s', 0.0)
    if not total or total <= 0.0:
        return None
    return round(snapshot.get('goodput_device_s', 0.0) / total, 4)


def data_stall_fraction(snapshot: dict):
    """Fraction of consumer step wall time the device (or the unfenced
    train loop) waited on data: pure pipeline stall plus the h2d staging
    seconds on the critical path, over the step wall. Same ``None``
    contract as :func:`goodput_fraction`."""
    total = snapshot.get('goodput_total_s', 0.0)
    if not total or total <= 0.0:
        return None
    stalled = (snapshot.get('goodput_stall_s', 0.0)
               + snapshot.get('goodput_h2d_s', 0.0))
    return round(stalled / total, 4)


def recommend_io_readahead(snapshot: dict, max_depth: int = 8) -> int:
    """Suggested ``io_readahead`` depth from a :meth:`ReaderStats.snapshot`.

    The worker-side ``depth='auto'`` controller applies the same formula to
    its live local measurements; this consumer-side variant lets users tune a
    fixed depth from ``reader.diagnostics`` after a profiling run. Effective
    read time (:func:`effective_io_s`) over decode time is the io:decode
    ratio; a pipeline needs roughly ``ceil(io / decode)`` reads in flight to
    keep decode fed."""
    import math
    io_s = effective_io_s(snapshot)
    decode_s = snapshot.get('worker_decode_s', 0.0)
    if io_s <= 0 or decode_s <= 0:
        return 1
    return int(min(max_depth, max(1, math.ceil(io_s / decode_s))))
