"""Out-of-process worker pool over ZeroMQ.

Reference parity: ``petastorm/workers_pool/process_pool.py`` — three-socket
topology PUSH(work)/PUB(control)/PULL(results) (:52-74), startup barrier
(:200-213), slow-joiner-resistant repeated stop broadcast (:284-301), orphan
monitor (:320-327,379-382), exception shipping (:260-263,399-405),
diagnostics (:303-312).

Deviation from the reference's ``[payload, control]`` framing: results travel
as ``[meta, control, buf0..bufN]`` multipart messages. Frame 0 is the
serializer's metadata frame, frame 1 the pickled control marker, and frames
2+ are out-of-band payload buffers (``ZeroCopySerializer`` ships each
ndarray/Arrow buffer as its own frame, so payload bytes are never copied
into a pickle blob). With ``zmq_copy_buffers=False`` the receive side hands
the serializer ``memoryview``s over the ZMQ frame buffers; each memoryview
keeps its frame (and the frame its underlying message) alive, so payloads
reconstructed as views — e.g. ``np.frombuffer`` over a frame — stay valid
for as long as the consumer holds them.

Workers are spawned as clean CPU-only interpreters via
:func:`petastorm_tpu.workers.exec_in_new_process.exec_in_new_process` so the
TPU runtime can never initialize outside the main process.
"""

from __future__ import annotations

import logging
import os
import pickle
import subprocess
import threading
import time
import traceback
from typing import Optional

from petastorm_tpu.lineage import LineageEnvelope
from petastorm_tpu.workers import (EmptyResultError, TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)
from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers.serializers import PickleSerializer, as_multipart
from petastorm_tpu.workers.stats import ReaderStats, finalize_item_times

logger = logging.getLogger(__name__)

_STARTUP_TIMEOUT_S = 60
_SHUTDOWN_TIMEOUT_S = 10

#: Default period of the worker-side liveness frame (override via
#: ``worker_args['heartbeat_interval_s']``). Low-frequency by design: it
#: exists for items that take minutes, not as a telemetry channel.
_HEARTBEAT_INTERVAL_S = 2.0
_LOCALHOST = 'tcp://127.0.0.1'

# Control markers travelling in the second multipart frame.
_DATA = 'DATA'
_FINISHED = 'FINISHED'

#: Work-queue marker requesting a clean worker retirement (live shrink,
#: docs/autotune.md): the receiving worker processes everything it already
#: holds, acks with :class:`_WorkerRetired`, and exits 0. Sent only after the
#: pool quiesced (ventilator paused, in-flight drained), so retirement can
#: never orphan a ventilated item.
_RETIRE = 'RETIRE'

#: Control-channel (PUB) marker carrying a live readahead-depth change to
#: every worker interpreter: ``(_SET_READAHEAD, depth)``.
_SET_READAHEAD = 'SET_READAHEAD'

#: Below this total payload size the worker lets ZMQ copy at send time:
#: zero-copy sends carry per-message bookkeeping (a free-fn callback and a
#: gc-pinned buffer) that only pays for itself on large frames.
_ZMQ_NOCOPY_SEND_THRESHOLD = 64 * 1024


class _WorkerStarted:
    def __init__(self, worker_id):
        self.worker_id = worker_id


class _WorkerTerminated:
    def __init__(self, worker_id):
        self.worker_id = worker_id


class _WorkerRetired:
    """Ack of a :data:`_RETIRE` marker: the worker finished everything it
    held, ran its shutdown hooks, and is exiting cleanly (exit code 0 — the
    liveness check must never read a retirement as a death)."""

    def __init__(self, worker_id):
        self.worker_id = worker_id


class _WorkerError:
    def __init__(self, exc, formatted):
        self.exc = exc
        self.formatted = formatted


class _WorkerHeartbeat:
    """Low-frequency liveness frame: a worker's current heartbeat records,
    sent every ``heartbeat_interval_s`` from a dedicated socket so an item
    that legitimately takes minutes still beats (the per-item piggyback in
    the accounting message only fires when an item *completes*)."""

    __slots__ = ('worker_id', 'records')

    def __init__(self, worker_id, records):
        self.worker_id = worker_id
        self.records = records


class ProcessPool:
    """Process-based pool implementing the ventilate/get_results protocol."""

    #: The worker bootstrap passes upcoming items to ``worker.prefetch_hint``
    #: — readers may enable ``io_readahead`` on this pool.
    supports_prefetch_hints = True

    def __init__(self, workers_count: int, serializer=None, zmq_copy_buffers: bool = True,
                 tracer=None, recovery=None):
        self._workers_count = workers_count
        self._serializer = as_multipart(serializer or PickleSerializer())
        self._zmq_copy_buffers = zmq_copy_buffers
        #: Worker auto-recovery options (``resilience.resolve_recovery``
        #: shape) or ``None`` — with recovery on, a crashed worker is
        #: respawned through the saved bootstrap and its in-flight items are
        #: re-ventilated exactly once (docs/robustness.md); with it off, a
        #: death stops the pool loudly (the pre-recovery behavior).
        self._recovery = recovery
        #: seq -> (args, kwargs) of every ventilated-but-unaccounted item —
        #: what recovery consults to know which items died with a worker.
        self._outstanding = {}
        self._next_item_seq = 0
        self._respawns_used = 0
        #: item key -> number of worker deaths the item was in flight for
        #: (the poison-item detector; see ``_finalize_recovery``).
        self._crash_counts = {}
        #: Live recovery episode state (None when not recovering).
        self._recovering = None
        # serializes concurrent _spawn_worker list mutations (a controller
        # resize racing a consumer-thread recovery respawn)
        self._spawn_mutex = threading.Lock()
        # refined from worker_args at start()
        self._hb_enabled = True
        self._hb_interval = _HEARTBEAT_INTERVAL_S
        #: Optional :class:`petastorm_tpu.tracing.Tracer`. Worker processes
        #: record spans locally and ship batches back inside the per-item
        #: accounting message (same pattern as the stage times); the pool
        #: merges them here with their original (pid, tid) tracks.
        self.tracer = tracer
        #: Optional :class:`petastorm_tpu.lineage.LineageTracker` (set by the
        #: Reader before :meth:`start`). Quarantine records arrive in the
        #: accounting message; per-item provenance rides the ``DATA`` control
        #: frame (payload frames stay zero-copy) and is re-wrapped into a
        #: :class:`~petastorm_tpu.lineage.LineageEnvelope` on this side.
        self.lineage = None
        self._processes = []
        self._procs_by_worker_id = {}
        self._next_worker_id = workers_count
        self._spawn_args = None
        self._readahead_depth_override = None
        # serializes concurrent resize calls; never nested with the
        # accounting lock's hot-path uses (resize is controller-thread-only)
        self._resize_lock = threading.Lock()
        # the control PUB socket is shared by stop()'s FINISHED broadcast
        # (consumer thread) and set_readahead_depth (controller thread);
        # ZMQ sockets are not thread-safe, so every send on it holds this
        # mutex (sends are to an in-proc queue — never a blocking wait)
        self._control_mutex = threading.Lock()
        self._retired_ack_ids = []
        self._ventilator = None
        self._context = None
        self._work_sender = None
        self._control_sender = None
        self._results_receiver = None
        self._poller = None
        self._stopped = False
        self._accounting_lock = threading.Lock()
        self._ventilated_items = 0
        self._processed_items = 0
        self._results_produced = 0
        self._terminated_workers = 0
        self.stats = ReaderStats()
        # Worker heartbeat records, refreshed from the per-item accounting
        # messages and the low-frequency _WorkerHeartbeat frames (both drain
        # through get_results on the consumer thread); read by the watchdog.
        # _last_drain marks the newest point records can be trusted up to:
        # a consumer that stops polling stops observing, and heartbeats()
        # must not let unobserved records age into false stalls.
        self._hb_lock = threading.Lock()
        self._heartbeats = {}
        self._last_drain = time.perf_counter()

    @property
    def workers_count(self) -> int:
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        import zmq
        self._context = zmq.Context()
        self._work_sender = self._context.socket(zmq.PUSH)
        work_port = self._work_sender.bind_to_random_port(_LOCALHOST)
        self._control_sender = self._context.socket(zmq.PUB)
        control_port = self._control_sender.bind_to_random_port(_LOCALHOST)
        self._results_receiver = self._context.socket(zmq.PULL)
        results_port = self._results_receiver.bind_to_random_port(_LOCALHOST)
        self._poller = zmq.Poller()
        self._poller.register(self._results_receiver, zmq.POLLIN)

        self._spawn_args = (worker_class, worker_args,
                            '{}:{}'.format(_LOCALHOST, work_port),
                            '{}:{}'.format(_LOCALHOST, control_port),
                            '{}:{}'.format(_LOCALHOST, results_port))
        # recovery's settle proof (see _maybe_finalize_recovery) needs the
        # worker heartbeat cadence and whether heartbeats flow at all
        args_dict = worker_args if isinstance(worker_args, dict) else {}
        self._hb_enabled = args_dict.get('health') is not False
        self._hb_interval = float(args_dict.get('heartbeat_interval_s',
                                                _HEARTBEAT_INTERVAL_S))
        for worker_id in range(self._workers_count):
            self._spawn_worker(worker_id)

        # Startup barrier: all workers must report in before we ventilate
        # (reference process_pool.py:200-213).
        started = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while started < self._workers_count:
            remaining_ms = max(0, (deadline - time.monotonic()) * 1000)
            if not dict(self._poller.poll(remaining_ms)):
                self.stop()
                self.join()
                raise TimeoutWaitingForResultError(
                    'Only {}/{} workers started within {}s'.format(
                        started, self._workers_count, _STARTUP_TIMEOUT_S))
            _, control = self._recv_multipart()
            if isinstance(control, _WorkerStarted):
                started += 1
            elif isinstance(control, _WorkerError):
                self.stop()
                self.join()
                raise control.exc

        self._ventilator = ventilator
        if ventilator is not None:
            ventilator.start()

    def _spawn_worker(self, worker_id: int) -> None:
        worker_class, worker_args, work_addr, control_addr, results_addr = \
            self._spawn_args
        if self._readahead_depth_override is not None \
                and isinstance(worker_args, dict):
            # a grow after a live set_readahead_depth must not resurrect the
            # construction-time depth: the PUB broadcast only reaches
            # workers whose SUB socket already joined, so the newcomer gets
            # the current depth in its spawn args instead
            worker_args = dict(worker_args,
                               io_readahead=self._readahead_depth_override)
        proc = exec_in_new_process(
            _worker_bootstrap,
            args=(worker_class, worker_id, worker_args, self._serializer,
                  work_addr, control_addr, results_addr, os.getpid()))
        with self._spawn_mutex:
            # copy-on-write rebind: readers (_check_workers_alive on the
            # consumer thread) iterate whatever list object they grabbed
            self._processes = self._processes + [proc]
            self._procs_by_worker_id[worker_id] = proc

    def _allocate_worker_id(self) -> int:
        with self._accounting_lock:
            worker_id = self._next_worker_id
            self._next_worker_id += 1
        return worker_id

    # -- live resize (the autotune controller's actuator; docs/autotune.md) ----

    def resize(self, workers_count: int, timeout_s: float = 30.0) -> int:
        """Live-resize the pool to ``workers_count`` worker interpreters.

        Growing spawns fresh workers through the existing bootstrap (they
        connect to the same sockets; ZMQ starts round-robining work to them
        as soon as they report in). Shrinking is **drain-then-retire**: the
        ventilator is paused, in-flight items drain to zero (the consumer
        keeps pulling results on its own thread), then :data:`_RETIRE`
        markers go out on the work socket — each is consumed by exactly one
        worker, which acks with :class:`_WorkerRetired` and exits 0. The
        retirement is a *clean handback*: no ventilated item is ever in
        flight toward a retiring worker, so the lineage ``CoverageAuditor``
        sees exactly-once delivery (contrast the killed-worker path, whose
        in-flight items surface as *reported drops*). Acks drain through
        ``get_results``; this thread reaps the exited interpreters (join
        off the hot path) and the ventilator resumes, redistributing all
        future items over the remaining workers.

        A quiesce or ack that cannot complete within ``timeout_s`` aborts
        the shrink safely (ventilator resumed, count untouched; a late ack
        still adjusts the count truthfully when it lands). Returns the live
        worker count."""
        if not isinstance(workers_count, int) or workers_count < 1:
            raise ValueError('workers_count must be a positive int, got '
                             '{!r}'.format(workers_count))
        with self._resize_lock:
            if self._stopped or self._spawn_args is None:
                return self._workers_count
            current = self._workers_count
            if workers_count > current:
                for _ in range(workers_count - current):
                    self._spawn_worker(self._allocate_worker_id())
                with self._accounting_lock:
                    self._workers_count += workers_count - current
                return self._workers_count
            if workers_count < current:
                self._retire_workers(current - workers_count, timeout_s)
            return self._workers_count

    def _retire_workers(self, k: int, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        vent = self._ventilator
        pause = getattr(vent, 'pause', None)
        if pause is not None:
            pause()
        in_flight = None
        acked = False
        try:
            # quiesce: no new ventilation, in-flight drains to zero — only
            # then can a retire marker be the sole message on the work
            # socket (nothing can be lost in a retiring worker's pipe).
            # BOTH counters must settle: the ventilator's own in_flight is
            # incremented BEFORE the work-socket send, so it covers the
            # admitted-but-not-yet-sent window the pool accounting misses
            # (and proves no other thread is mid-send on the PUSH socket
            # when the markers go out).
            while time.monotonic() < deadline:
                with self._accounting_lock:
                    in_flight = self._ventilated_items - self._processed_items
                vent_in_flight = getattr(vent, 'in_flight', 0) if vent else 0
                if in_flight == 0 and vent_in_flight == 0:
                    break
                time.sleep(0.02)
            else:
                logger.warning('pool shrink aborted: %d items still in '
                               'flight after %.1fs', in_flight, timeout_s)
                return
            target = self._workers_count - k
            for _ in range(k):
                self._work_sender.send_pyobj(_RETIRE)
            # acks drain through get_results (consumer thread); each one
            # decrements the live count the moment it lands
            while time.monotonic() < deadline:
                with self._accounting_lock:
                    if self._workers_count <= target:
                        acked = True
                        break
                time.sleep(0.02)
            self.reap_retired(max(0.0, deadline - time.monotonic()))
        finally:
            if not acked:
                # a marker may still be unconsumed (e.g. the consumer is
                # not draining acks): give the retiring interpreter's
                # disconnect a moment to propagate to the PUSH side before
                # new items may ventilate, so round-robin cannot route one
                # into a closing pipe; the worker's own final drain (see
                # _worker_bootstrap) covers the other side of this window
                time.sleep(0.25)
            resume = getattr(vent, 'resume', None)
            if resume is not None:
                resume()

    def _on_worker_retired(self, worker_id) -> None:
        """Consumer-thread handler for a :class:`_WorkerRetired` ack: adjust
        the live count; the actual process reap happens off the hot path in
        :meth:`reap_retired`."""
        with self._accounting_lock:
            self._workers_count = max(0, self._workers_count - 1)
            self._retired_ack_ids.append(worker_id)

    def reap_retired(self, timeout_s: float = 10.0) -> int:
        """Wait out (and drop) the processes of acked retirements; returns
        how many were reaped. An acked retiree has already exited, so the
        waits settle immediately — cheap enough for the teardown path."""
        with self._accounting_lock:
            acked, self._retired_ack_ids = self._retired_ack_ids, []
        deadline = time.monotonic() + timeout_s
        for worker_id in acked:
            proc = self._procs_by_worker_id.pop(worker_id, None)
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
            self._processes = [p for p in self._processes if p is not proc]
        return len(acked)

    def set_readahead_depth(self, depth: int) -> None:
        """Broadcast a live readahead-depth change to every worker
        interpreter over the control channel (the same PUB socket the stop
        broadcast uses; serialized against it by the control mutex); workers
        spawned by a later grow inherit it via their spawn args."""
        self._readahead_depth_override = int(depth)
        with self._control_mutex:
            if self._control_sender is not None and not self._stopped:
                self._control_sender.send_pyobj((_SET_READAHEAD, int(depth)))

    def _recv_multipart(self):
        """Receive one ``[meta, control, buf0..bufN]`` message; returns
        ``(payload_frames, control)`` where ``payload_frames`` is the list of
        payload buffers (metadata frame first, out-of-band buffers after).

        With ``zmq_copy_buffers=False`` the payload frames are memoryviews
        over the ZMQ frame buffers. Lifetime: each memoryview references its
        ``zmq.Frame`` (``memoryview.obj``), which pins the underlying libzmq
        message — so views the serializer builds over these buffers (numpy
        ``frombuffer``, ``pa.py_buffer``) remain valid while referenced. The
        frames list itself must NOT be sliced into raw ``Frame.bytes`` lazily
        later: converting here, once, is the contract."""
        frames = self._results_receiver.recv_multipart(
            copy=self._zmq_copy_buffers)
        if not self._zmq_copy_buffers:
            control_bytes = frames[1].bytes
            payload_frames = [frames[0].buffer] + [f.buffer for f in frames[2:]]
        else:
            control_bytes = frames[1]
            payload_frames = [frames[0]] + frames[2:]
        return payload_frames, pickle.loads(control_bytes)

    def ventilate(self, *args, **kwargs):
        with self._accounting_lock:
            self._ventilated_items += 1
            seq = self._next_item_seq
            self._next_item_seq += 1
            self._outstanding[seq] = (args, kwargs)
        self._work_sender.send_pyobj((seq, args, kwargs))

    def _all_work_consumed(self) -> bool:
        with self._accounting_lock:
            counts_settled = self._ventilated_items == self._processed_items
        if not counts_settled:
            return False
        if self._ventilator is not None:
            return self._ventilator.completed()
        return True

    def get_results(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        entered = time.perf_counter()
        while True:
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutWaitingForResultError(
                    'No results after {:.1f}s'.format(timeout))
            wait_start = time.perf_counter()
            ready = dict(self._poller.poll(100))
            now = time.perf_counter()
            self.stats.add_time('queue_wait_s', now - wait_start)
            with self._hb_lock:
                self._last_drain = now
            if not ready:
                if self._all_work_consumed():
                    raise EmptyResultError()
                self._check_workers_alive()
                self._maybe_finalize_recovery()
                continue
            payload_frames, control = self._recv_multipart()
            if isinstance(control, VentilatedItemProcessedMessage):
                with self._accounting_lock:
                    self._processed_items += 1
                    in_flight = self._ventilated_items - self._processed_items
                    if control.seq is not None:
                        self._outstanding.pop(control.seq, None)
                self._note_recovery_progress()
                self._merge_item_stats(getattr(control, 'stats', None))
                self.stats.gauge('queue_depth', in_flight)
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                # Eager end-of-data check (mirrors ThreadPool.get_results):
                # detect completion on the final accounting message instead of
                # waiting out the next 100ms poll.
                if self._all_work_consumed():
                    raise EmptyResultError()
                continue
            if isinstance(control, _WorkerError):
                import sys
                sys.stderr.write(control.formatted)
                self.stop()
                raise control.exc
            if isinstance(control, _WorkerHeartbeat):
                self._merge_heartbeats(control.records)
                continue
            if isinstance(control, _WorkerRetired):
                # live-shrink ack (see resize): adjust the count here, reap
                # the interpreter off the hot path on the resizing thread
                self._on_worker_retired(control.worker_id)
                continue
            if isinstance(control, _WorkerStarted):
                # a replacement worker spawned by recovery reported in:
                # redispatch may proceed once every replacement is connected
                recovering = self._recovering
                if recovering is not None:
                    recovering['awaiting_start'].discard(control.worker_id)
                continue
            provenance = None
            if isinstance(control, tuple) and len(control) == 2 \
                    and control[0] == _DATA:
                control, provenance = control
            if control == _DATA:
                self._note_recovery_progress()
                with self._accounting_lock:
                    self._results_produced += 1
                copies_before = getattr(self._serializer, 'copies', 0)
                deser_start = time.perf_counter()
                with self.stats.timed('deserialize_s'):
                    result = self._serializer.deserialize_multipart(payload_frames)
                now = time.perf_counter()
                self.stats.record_latency('queue_wait', deser_start - entered)
                self.stats.record_latency('deserialize', now - deser_start)
                if self.tracer is not None:
                    self.tracer.add_span('queue_wait', 'consumer', entered,
                                         deser_start - entered)
                    self.tracer.add_span('deserialize', 'transport',
                                         deser_start, now - deser_start)
                # consumer-side deserialize copies count too (worker-side
                # copies arrive via the accounting message) — the counter
                # must cover both ends of the hop
                consumer_copies = getattr(self._serializer, 'copies', 0) - copies_before
                if consumer_copies:
                    self.stats.add('payload_copies', consumer_copies)
                self.stats.add('bytes_moved',
                               sum(_nbytes(f) for f in payload_frames))
                self.stats.add('payload_frames', len(payload_frames))
                self.stats.add('items_out')
                if provenance is not None:
                    result = LineageEnvelope(result, provenance)
                return result
            # _WorkerStarted duplicates / stray messages are ignored.

    def _merge_heartbeats(self, records):
        if not records:
            return
        with self._hb_lock:
            self._heartbeats.update(records)

    def heartbeats(self):
        """Latest heartbeat records shipped back by the worker interpreters.
        Fresh as of the last drained accounting/heartbeat frame — the
        consumer's ``get_results`` poll loop keeps draining while it waits,
        so records stay live even when no item completes.

        Record ages are clamped to the last drain point: when the CONSUMER
        stops polling (a long train step, a checkpoint pause), shipped
        records stop refreshing through no fault of the workers, so each
        record is reported at the age it had when last observed. A wedged
        worker resumes aging the moment the consumer polls again."""
        with self._hb_lock:
            records = dict(self._heartbeats)
            gap = max(0.0, time.perf_counter() - self._last_drain)
        if not gap:
            return records
        return {entity: dict(record, ts=record.get('ts', 0.0) + gap)
                for entity, record in records.items()}

    def _merge_item_stats(self, item_stats):
        if not item_stats:
            return
        self.stats.merge_times(item_stats.get('times'))
        self.stats.merge_counts(item_stats.get('counts'))
        self.stats.merge_gauges(item_stats.get('gauges'))
        self.stats.merge_latency(item_stats.get('latency'))
        self._merge_heartbeats(item_stats.get('heartbeats'))
        if self.lineage is not None and item_stats.get('quarantines'):
            self.lineage.add_quarantines(item_stats['quarantines'])
        if self.lineage is not None:
            for prov in item_stats.get('empty_publishes', ()):
                self.lineage.register(prov)
        if self.tracer is not None:
            self.tracer.merge(item_stats.get('spans'))
        for counter in ('payload_copies',):
            n = item_stats.get(counter)
            if n:
                self.stats.add(counter, n)

    def _check_workers_alive(self):
        dead = [p for p in self._processes if p.poll() not in (None, 0)]
        if not dead or self._stopped:
            return
        codes = [p.returncode for p in dead]
        recovery = self._recovery
        if recovery is not None:
            budget = recovery.get('max_respawns')
            if budget is None:
                budget = max(3, self._workers_count)
            if self._respawns_used + len(dead) <= budget:
                self._begin_recovery(dead, codes)
                return
            logger.error('worker respawn budget exhausted (%d used, %d '
                         'dead, budget %d): stopping the pool',
                         self._respawns_used, len(dead), budget)
        self.stop()
        raise RuntimeError('Worker process(es) died with exit codes {}'.format(codes))

    # -- worker auto-recovery (docs/robustness.md) -----------------------------

    def _begin_recovery(self, dead, codes) -> None:
        """Consumer-thread entry of one recovery episode: replace the dead
        interpreters through the saved bootstrap, pause the ventilator, and
        start the settle clock. The episode finalizes (redispatch) once the
        survivors drained and every replacement reported in — in the
        meantime results keep flowing to the caller normally."""
        dead_pids = {p.pid for p in dead}
        dead_ids = [wid for wid, p in list(self._procs_by_worker_id.items())
                    if p in dead]
        logger.warning('worker process(es) %s died with exit codes %s; '
                       'respawning and re-ventilating their in-flight items',
                       dead_ids, codes)
        with self._spawn_mutex:
            self._processes = [p for p in self._processes if p not in dead]
            for wid in dead_ids:
                self._procs_by_worker_id.pop(wid, None)
        # a dead worker's last heartbeat must not age into a false stall
        # verdict against an entity that no longer exists
        with self._hb_lock:
            self._heartbeats = {
                entity: record for entity, record in self._heartbeats.items()
                if record.get('pid') not in dead_pids}
        vent = self._ventilator
        pause = getattr(vent, 'pause', None)
        if pause is not None:
            pause()
        replacements = set()
        for _ in dead:
            worker_id = self._allocate_worker_id()
            self._spawn_worker(worker_id)
            replacements.add(worker_id)
        self._respawns_used += len(dead)
        self.stats.add('worker_respawns', len(dead))
        now = time.monotonic()
        if self._recovering is not None:
            # a replacement died while an episode was still settling: fold
            # the new spawns in and restart the settle clock
            self._recovering['awaiting_start'] |= replacements
            self._recovering['last_progress'] = now
        else:
            self._recovering = {'awaiting_start': replacements,
                                'last_progress': now}

    def _note_recovery_progress(self) -> None:
        if self._recovering is not None:
            self._recovering['last_progress'] = time.monotonic()

    def _maybe_finalize_recovery(self) -> None:
        """Finalize a settling recovery episode: once (a) every replacement
        connected, (b) no item has completed for the settle window, and (c)
        every surviving worker's heartbeat shows an idle-class stage, the
        remaining outstanding items are exactly the ones that died with the
        crashed worker(s).

        Why (c) and the settle floor make redispatch exactly-once: a
        survivor that starts an item beats a non-idle stage, and the pool's
        view of that beat is at most one heartbeat interval stale — so with
        the settle window floored at ``1.25 x heartbeat_interval_s``, an
        item a survivor began can never look both "no progress for the
        whole window" AND "worker idle" at once. An item a survivor still
        holds therefore always blocks finalize, and only truly-lost items
        are re-ventilated."""
        recovering = self._recovering
        if recovering is None or self._stopped:
            return
        if recovering['awaiting_start']:
            return
        settle_s = (self._recovery or {}).get('settle_s', 1.0)
        if self._hb_enabled:
            settle_s = max(settle_s, 1.25 * self._hb_interval)
        if time.monotonic() - recovering['last_progress'] < settle_s:
            return
        if self._hb_enabled:
            from petastorm_tpu.health import IDLE_STAGES
            with self._hb_lock:
                records = dict(self._heartbeats)
            for entity, record in records.items():
                if entity.startswith('worker-') \
                        and record.get('stage') not in IDLE_STAGES:
                    return   # a survivor is mid-item; keep waiting
        self._recovering = None
        self._finalize_recovery()

    @staticmethod
    def _item_key(seq, kwargs):
        """Stable identity of a ventilated item across epochs (poison
        accounting): the reader's items are kwargs dicts carrying
        ``piece_index``/``shuffle_row_drop_partition``; anything else keys
        by its seq (poison detection then only spans one dispatch)."""
        piece_index = kwargs.get('piece_index')
        if piece_index is None:
            return ('seq', seq)
        return (piece_index,
                tuple(kwargs.get('shuffle_row_drop_partition') or (0, 1)))

    def _synthesize_processed(self, seq) -> None:
        """Retire an outstanding item WITHOUT redispatching it (it was
        already delivered/quarantined): the accounting the dead worker never
        sent is synthesized here so the epoch's counts settle."""
        with self._accounting_lock:
            self._processed_items += 1
            self._outstanding.pop(seq, None)
        if self._ventilator is not None:
            self._ventilator.processed_item()

    def _finalize_recovery(self) -> None:
        from petastorm_tpu.lineage import crash_quarantine_record
        with self._accounting_lock:
            lost = sorted(self._outstanding.items())
        poison_threshold = (self._recovery or {}).get('poison_threshold', 3)
        tracker = self.lineage if (self.lineage is not None
                                   and self.lineage.enabled) else None
        plan = []
        for seq, (args, kwargs) in lost:
            key = self._item_key(seq, kwargs)
            count = self._crash_counts.get(key, 0) + 1
            self._crash_counts[key] = count
            plan.append((count, seq, args, kwargs, key))
        redispatched = 0
        # repeat offenders go LAST: innocents lost in a poison item's blast
        # radius complete before the next crash, so only the item that
        # keeps killing workers accumulates toward the threshold
        for count, seq, args, kwargs, key in sorted(
                plan, key=lambda entry: (entry[0], entry[1])):
            epoch = kwargs.get('epoch', 0)
            piece_index = kwargs.get('piece_index')
            partition = kwargs.get('shuffle_row_drop_partition', (0, 1))
            deficit = (tracker.delivery_deficit(epoch, piece_index, partition)
                       if tracker is not None else None)
            if deficit is not None and deficit <= 0:
                # the worker published this item's payload and died before
                # the accounting frame: it WAS delivered — redispatching it
                # would be the duplicate the auditor exists to catch
                self._synthesize_processed(seq)
                continue
            if count >= poison_threshold:
                logger.error('poison item %s killed %d worker(s); '
                             'quarantining it instead of crash-looping', key,
                             count)
                if tracker is not None and piece_index is not None:
                    tracker.add_quarantines([crash_quarantine_record(
                        tracker, piece_index, epoch, partition, count)])
                self.stats.add('poison_items_quarantined')
                self._synthesize_processed(seq)
                continue
            self._work_sender.send_pyobj((seq, args, kwargs))
            redispatched += 1
        if redispatched:
            self.stats.add('items_redispatched', redispatched)
            logger.warning('re-ventilated %d in-flight item(s) lost with '
                           'crashed worker(s)', redispatched)
        resume = getattr(self._ventilator, 'resume', None)
        if resume is not None:
            resume()

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        # acked retirees already exited 0 but may still sit in
        # self._processes until the next controller reap — count them out
        # now or the termination wait below spins its full timeout
        self.reap_retired(timeout_s=2.0)
        # Repeated FINISHED broadcast beats the PUB/SUB slow-joiner race
        # (reference process_pool.py:284-301). Drain results while waiting.
        deadline = time.monotonic() + _SHUTDOWN_TIMEOUT_S
        while self._terminated_workers < len(self._processes) and time.monotonic() < deadline:
            with self._control_mutex:
                self._control_sender.send_pyobj(_FINISHED)
            if dict(self._poller.poll(50)):
                try:
                    _, control = self._recv_multipart()
                    if isinstance(control, _WorkerTerminated):
                        self._terminated_workers += 1
                    elif isinstance(control, _WorkerRetired):
                        # a late shrink ack arriving during teardown: that
                        # worker is exiting too — count it or the loop
                        # waits out the full timeout for a ghost
                        self._on_worker_retired(control.worker_id)
                        self._terminated_workers += 1
                # teardown drain: ANY failure here means the transport is
                # closing under us, which is the condition being handled —
                # swallowing OSError is the intended semantics
                except Exception:  # petalint: disable=exception-hygiene
                    break

    def join(self):
        for proc in self._processes:
            try:
                proc.wait(timeout=_SHUTDOWN_TIMEOUT_S)
            except subprocess.TimeoutExpired:
                proc.kill()
        for sock in (self._work_sender, self._control_sender, self._results_receiver):
            if sock is not None:
                sock.close(linger=0)
        if self._context is not None:
            self._context.term()

    @property
    def diagnostics(self):
        with self._accounting_lock:
            out = {
                'items_consumed': self._processed_items,
                'items_produced': self._results_produced,
                'items_inprocess': self._ventilated_items - self._processed_items,
                'zmq_copy_buffers': self._zmq_copy_buffers,
            }
        out.update(self.stats.snapshot())
        return out


def _nbytes(frame) -> int:
    nbytes = getattr(frame, 'nbytes', None)
    if nbytes is not None:
        return nbytes
    size = getattr(frame, 'size', None)       # pa.Buffer
    if isinstance(size, int):
        return size
    return len(frame)


def _worker_bootstrap(worker_class, worker_id, worker_args, serializer,
                      work_addr, control_addr, results_addr, parent_pid):
    """Entry point of a spawned worker interpreter
    (reference ``_worker_bootstrap``, ``process_pool.py:330-413``)."""
    import zmq

    # Orphan protection: if the parent dies, exit immediately
    # (reference process_pool.py:320-327).
    def monitor_parent():
        while True:
            try:
                os.kill(parent_pid, 0)
            except OSError:
                os._exit(0)
            time.sleep(1)

    threading.Thread(target=monitor_parent, daemon=True,
                     name='petastorm-tpu-parent-monitor').start()

    serializer = as_multipart(serializer)
    context = zmq.Context()
    work_receiver = context.socket(zmq.PULL)
    work_receiver.connect(work_addr)
    control_receiver = context.socket(zmq.SUB)
    control_receiver.setsockopt(zmq.SUBSCRIBE, b'')
    control_receiver.connect(control_addr)
    results_sender = context.socket(zmq.PUSH)
    results_sender.connect(results_addr)

    # Per-item stage accounting, shipped back inside the processed-item
    # control message (the consumer-side pool merges it into its stats).
    item = {'serialize_s': 0.0, 'publish_wait_s': 0.0, 'copies_before': 0}
    trace_enabled = isinstance(worker_args, dict) and bool(worker_args.get('trace'))
    # bootstrap-level spans (serialize, process_item) ride back with the
    # worker's own spans in the accounting message; (pid, tid) attribution
    # keeps each worker interpreter on its own trace track
    item_spans = []
    trace_pid = os.getpid()

    # set once the worker exists: lets send() mark time blocked on a full
    # results socket as idle-class back-pressure (a slow/paused consumer,
    # not a wedged worker — same exemption as ThreadPool._put_result)
    publish_beat = {'fn': None}

    def send(payload_frames, control):
        message = [payload_frames[0], pickle.dumps(control)] + list(payload_frames[1:])
        # Zero-copy send for large payloads: libzmq reads the buffers in
        # place (workers drop their reference right after publishing, so
        # nothing mutates them post-send). Small/control messages take the
        # plain copying path.
        nocopy = sum(_nbytes(f) for f in payload_frames) >= _ZMQ_NOCOPY_SEND_THRESHOLD
        start = time.perf_counter()
        try:
            results_sender.send_multipart(message, copy=not nocopy,
                                          flags=zmq.NOBLOCK)
        except zmq.Again:   # HWM reached: the consumer is the slow side
            beat = publish_beat['fn']
            if beat is not None:
                beat('backpressured')
            results_sender.send_multipart(message, copy=not nocopy)
            if beat is not None:
                beat('processing')
        item['publish_wait_s'] += time.perf_counter() - start

    def publish(data):
        # Lineage envelopes are unwrapped HERE: the provenance record rides
        # in the pickled control frame next to the DATA marker, so the
        # payload serializer (and its zero-copy frames) never sees it.
        provenance = None
        if isinstance(data, LineageEnvelope):
            provenance = data.provenance
            data = data.payload
        start = time.perf_counter()
        frames = serializer.serialize_multipart(data)
        serialized = time.perf_counter()
        item['serialize_s'] += serialized - start
        if trace_enabled:
            item_spans.append(('serialize', 'transport', start,
                               serialized - start, trace_pid,
                               threading.get_ident(), None))
        send(frames, _DATA if provenance is None else (_DATA, provenance))

    try:
        worker = worker_class(worker_id, publish, worker_args)
    except (OSError, MemoryError) as e:
        # infra failure (NEVER_QUARANTINE class): ship it, then die loudly —
        # a nonzero child exit reaches the parent's liveness check even when
        # the error frame is lost in a closing transport
        send([b''], _WorkerError(e, traceback.format_exc()))
        raise
    except Exception as e:
        send([b''], _WorkerError(e, traceback.format_exc()))
        return
    send([b''], _WorkerStarted(worker_id))

    # Low-frequency liveness frames: the accounting message only carries a
    # heartbeat when an item COMPLETES, so a legitimate minutes-long item
    # (or a wedged one — the case the watchdog exists for) would look dead.
    # A dedicated thread ships the worker's current records every interval.
    # ZMQ sockets are not thread-safe: this thread owns its own PUSH socket
    # (contexts are shareable, sockets are not) and closes it itself so the
    # final context.term() cannot hang on it.
    hb_stop = threading.Event()
    hb_thread = None
    hb_snapshot = getattr(worker, 'heartbeat_snapshot', None)
    health_on = not (isinstance(worker_args, dict)
                     and worker_args.get('health') is False)
    if health_on:
        publish_beat['fn'] = getattr(worker, 'beat', None)
    if health_on and hb_snapshot is not None:
        hb_interval = (worker_args.get('heartbeat_interval_s',
                                       _HEARTBEAT_INTERVAL_S)
                       if isinstance(worker_args, dict)
                       else _HEARTBEAT_INTERVAL_S)

        def hb_loop():
            sock = context.socket(zmq.PUSH)
            sock.connect(results_addr)
            try:
                while not hb_stop.wait(hb_interval):
                    try:
                        # NOBLOCK: a blocking send with the consumer gone or
                        # not draining would be uninterruptible by hb_stop,
                        # leaving the socket open and wedging context.term()
                        # at worker exit. Dropping a liveness frame is free —
                        # the next tick carries fresher records anyway.
                        sock.send_multipart(
                            [b'', pickle.dumps(_WorkerHeartbeat(
                                worker_id, hb_snapshot()))],
                            flags=zmq.NOBLOCK)
                    except zmq.Again:
                        continue
            except zmq.ZMQError:
                pass   # pool tearing down under us
            finally:
                sock.close(linger=0)

        hb_thread = threading.Thread(target=hb_loop, daemon=True,
                                     name='petastorm-tpu-worker-heartbeat')
        hb_thread.start()

    poller = zmq.Poller()
    poller.register(work_receiver, zmq.POLLIN)
    poller.register(control_receiver, zmq.POLLIN)
    # Readahead lookahead: ZMQ PUSH round-robins items to worker PULL sockets
    # at send time, so everything this socket holds is already this worker's.
    # Workers exposing prefetch_lookahead > 0 drain up to that many extra
    # items into a local FIFO and get hinted about them before processing the
    # head, letting their background reader overlap the next reads with the
    # current decode.
    from collections import deque
    pending = deque()
    hint = getattr(worker, 'prefetch_hint', None)
    retiring = False
    try:
        while True:
            # block only when there is nothing to process; otherwise just
            # drain whatever already arrived
            socks = dict(poller.poll(None if not pending else 0))
            if control_receiver in socks:
                msg = control_receiver.recv_pyobj()
                if msg == _FINISHED:
                    break   # drop un-processed lookahead items: pool stopping
                if (isinstance(msg, tuple) and len(msg) == 2
                        and msg[0] == _SET_READAHEAD):
                    # live knob broadcast (docs/autotune.md): applied between
                    # items on the worker's own thread
                    setter = getattr(worker, 'set_readahead_depth', None)
                    if setter is not None:
                        setter(msg[1])
            if work_receiver in socks and not retiring:
                lookahead = getattr(worker, 'prefetch_lookahead', 0)
                while len(pending) - 1 < lookahead:
                    try:
                        entry = work_receiver.recv_pyobj(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    if entry == _RETIRE:
                        # clean retirement: stop pulling, finish what we
                        # hold, ack, exit 0 (see ProcessPool.resize)
                        retiring = True
                        break
                    pending.append(entry)   # (seq, args, kwargs)
            if retiring and not pending:
                # final drain: anything that slipped into our pipe behind
                # the marker is processed, not orphaned (the quiesce makes
                # this empty in the normal path; a timed-out shrink that
                # resumed ventilation early is the case this covers)
                while True:
                    try:
                        entry = work_receiver.recv_pyobj(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    if entry != _RETIRE:
                        pending.append(entry)
                if pending:
                    continue
                send([b''], _WorkerRetired(worker_id))
                break
            if not pending:
                continue
            if hint is not None:
                # whole pending FIFO, head included (the readahead treats its
                # outstanding reads as a prefix of this list); the seq tag is
                # pool accounting, not part of the worker-facing item shape
                hint([(h_args, h_kwargs) for _seq, h_args, h_kwargs
                      in pending])
            seq, args, kwargs = pending.popleft()
            if health_on and hasattr(worker, 'beat'):
                worker.beat('processing')
            item['serialize_s'] = 0.0
            item['publish_wait_s'] = 0.0
            item['copies_before'] = getattr(serializer, 'copies', 0)
            process_start = time.perf_counter()
            try:
                worker.process(*args, **kwargs)
            except (OSError, MemoryError) as e:
                # infra failure (NEVER_QUARANTINE class): ship it, then stop
                # serving from a broken resource — the raise runs the full
                # teardown path below (terminated frame, socket close) and
                # exits the child nonzero for the parent's liveness check
                send([b''], _WorkerError(e, traceback.format_exc()))
                raise
            except Exception as e:
                send([b''], _WorkerError(e, traceback.format_exc()))
            elapsed = time.perf_counter() - process_start
            times = worker.drain_stage_times() \
                if hasattr(worker, 'drain_stage_times') else {}
            transport = item['serialize_s'] + item['publish_wait_s']
            times['serialize_s'] = times.get('serialize_s', 0.0) \
                + item['serialize_s']
            times['worker_publish_wait_s'] = \
                times.get('worker_publish_wait_s', 0.0) + item['publish_wait_s']
            finalize_item_times(times, elapsed, transport_s=transport)
            item_stats = {
                'times': times,
                'payload_copies': getattr(serializer, 'copies', 0)
                - item['copies_before'],
            }
            if hasattr(worker, 'drain_stat_counts'):
                counts, gauges = worker.drain_stat_counts()
                if counts:
                    item_stats['counts'] = counts
                if gauges:
                    item_stats['gauges'] = gauges
            if hasattr(worker, 'drain_latency'):
                # bucket-count deltas ride the accounting message like
                # merge_counts: worker death loses only unshipped deltas
                latency_deltas = worker.drain_latency()
                if latency_deltas:
                    item_stats['latency'] = latency_deltas
            if hasattr(worker, 'drain_quarantines'):
                quarantines = worker.drain_quarantines()
                if quarantines:
                    item_stats['quarantines'] = quarantines
            if hasattr(worker, 'drain_empty_publishes'):
                empty = worker.drain_empty_publishes()
                if empty:
                    item_stats['empty_publishes'] = empty
            if hasattr(worker, 'item_done'):
                worker.item_done()
            if health_on and hb_snapshot is not None:
                item_stats['heartbeats'] = hb_snapshot()
            if trace_enabled:
                item_spans.append(('process_item', 'worker', process_start,
                                   elapsed, trace_pid, threading.get_ident(),
                                   None))
                spans = item_spans + (worker.drain_spans()
                                      if hasattr(worker, 'drain_spans') else [])
                item_spans = []
                item_stats['spans'] = spans
            send([b''], VentilatedItemProcessedMessage(stats=item_stats,
                                                       seq=seq))
            if health_on and publish_beat['fn'] is not None:
                # the accounting send's back-pressure path resumes at
                # 'processing'; between items the truthful stage is idle
                publish_beat['fn']('idle')
    finally:
        if publish_beat['fn'] is not None:
            publish_beat['fn']('stopped')
        hb_stop.set()
        if hb_thread is not None:
            hb_thread.join(timeout=5)
        worker.shutdown()
        if not retiring:
            # a retiree already acked with _WorkerRetired; a second
            # terminated frame would let stop() double-count it and exit
            # its broadcast loop before live workers acked
            send([b''], _WorkerTerminated(worker_id))
        for sock in (work_receiver, control_receiver, results_sender):
            sock.close(linger=1000)
        context.term()
